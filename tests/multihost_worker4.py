"""Slim worker for the 4-process cluster test: one process of a 4-process
jax distributed cluster, 2 virtual CPU devices each (8 global). Proves the
DCN story scales past 2 processes: the full engine shuffle (device
exchange + allgather reconvergence) with a STRING payload and a grouped
aggregation, against an exact oracle computed from the full dataset.

Run: python multihost_worker4.py <process_id> <num_processes> <port>
"""

import os
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import collections  # noqa: E402

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from daft_tpu.parallel.multihost import global_mesh, init_distributed  # noqa: E402

assert init_distributed(f"localhost:{port}", nproc, pid)
assert len(jax.devices()) == 2 * nproc
assert len(jax.local_devices()) == 2

mesh = global_mesh()

import daft_tpu as dtp  # noqa: E402
from daft_tpu import col  # noqa: E402
from daft_tpu.context import get_context  # noqa: E402
from daft_tpu.runners import MeshRunner  # noqa: E402

ctx = get_context()
ctx._runner = MeshRunner(mesh=mesh)
cfg = ctx.execution_config
cfg.use_device_kernels = True
cfg.device_min_rows = 1
cfg.enable_result_cache = False
cfg.executor_threads = 1  # SPMD discipline: identical collective order

# identical control plane on every process (same seed)
rng = np.random.RandomState(5)
svals = [None if i % 17 == 0 else f"g{i % 29:02d}" for i in range(6000)]
keys = rng.randint(0, 32, 6000).astype(np.int64)
vals = rng.rand(6000)

df = (dtp.from_pydict({
    "g": dtp.Series.from_pylist(svals, "g", dtp.DataType.string()),
    "k": keys, "v": vals})
    .repartition(8, "k")
    .groupby("g").agg(col("v").sum().alias("s"), col("v").count().alias("c"))
    .sort("g"))
coll = df.collect()
_counters = coll.stats.snapshot()["counters"]
# the exchange is allowed to ride EITHER plane: the device collective, or
# the dist/ peer transport when the jaxlib backend has no cross-process
# collective (the gap the probe below names)
shuffles = (_counters.get("device_shuffles", 0)
            + _counters.get("transport_shuffles", 0))
if shuffles < 1:
    # the exchange failure was swallowed by the collective breaker: probe a
    # minimal cross-process collective DIRECTLY so the root cause is in our
    # output (the parent test xfails only on the known jaxlib CPU
    # multiprocess-collective gap, and fails loudly on anything else)
    try:
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        arr = jax.device_put(
            jnp.arange(mesh.devices.size, dtype=jnp.int32),
            NamedSharding(mesh, P(mesh.axis_names[0])))
        from daft_tpu.parallel.collectives import _shard_map

        probe = _shard_map(
            lambda x: jax.lax.psum(x, mesh.axis_names[0]), mesh=mesh,
            in_specs=P(mesh.axis_names[0]), out_specs=P())
        jax.block_until_ready(probe(arr))
        print("COLLECTIVE_PROBE_OK")
    except Exception as e:  # the root cause the breaker swallowed
        print(f"COLLECTIVE_PROBE_FAILED: {type(e).__name__}: {e}")
    raise AssertionError(
        f"device exchange never engaged: {coll.stats.snapshot()}")

acc_s = collections.defaultdict(float)
acc_c = collections.defaultdict(int)
for g, v in zip(svals, vals):
    acc_s[g] += v
    acc_c[g] += 1
gd = coll.to_pydict()
want_keys = sorted(k for k in acc_c if k is not None)
got_nonnull = [k for k in gd["g"] if k is not None]
assert got_nonnull == want_keys, (got_nonnull[:5], want_keys[:5])
for g, s, c in zip(gd["g"], gd["s"], gd["c"]):
    assert c == acc_c[g], (g, c, acc_c[g])
    # x64 off in this worker (real-TPU config): f64 sums compute as f32
    assert abs(s - acc_s[g]) <= max(1e-5 * abs(acc_s[g]), 1e-6), (g, s)

print(f"MULTIHOST4_OK {pid} shuffles={shuffles}", flush=True)
