"""Self-healing data plane (ISSUE 12): end-to-end partition integrity,
lineage recomputation, and speculative straggler mitigation.

Covers the acceptance matrix {spill sync, spill async, encoded exchange
payload, transport frame} x {clean, bit-flip via fault site} x
{recompute succeeds, lineage truncated}: every recovered query must be
byte-identical to the clean run with exact
``partitions_recomputed``/``tasks_speculated`` counter accounting (zero
with the knobs off), plus the disk-full spill classification, the
cross-process-stable python-object hash, and the health/record surfaces.
"""

import errno
import json
import os
import socket
import subprocess
import sys
import time
from collections import deque

import pytest

import daft_tpu as dt
from daft_tpu import col, faults
from daft_tpu.context import get_context, set_execution_config
from daft_tpu.errors import DaftCorruptionError, DaftError, DaftValueError
from daft_tpu.dist import supervisor as sup

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset():
    cfg_before = get_context().execution_config
    faults.disarm()
    yield
    faults.disarm()
    get_context().execution_config = cfg_before


@pytest.fixture(scope="module", autouse=True)
def _module_teardown():
    yield
    sup.shutdown_worker_pool()
    os.environ.pop(faults.ENV_FAULT_SPEC, None)
    assert sup.live_worker_process_count() == 0


@pytest.fixture(scope="module")
def parquet_dir(tmp_path_factory):
    """Scan-backed source files: the stable storage lineage recipes
    re-read from."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = tmp_path_factory.mktemp("integrity_src")
    for i in range(4):
        n = 8000
        pq.write_table(
            pa.table({
                "a": list(range(i * n, (i + 1) * n)),
                "b": [j % 7 for j in range(n)],
                "g": [f"g{j % 5}" for j in range(n)],  # low-card: encodes
            }), str(d / f"p{i}.parquet"))
    return str(d)


def _scan_query(parquet_dir):
    return (dt.read_parquet(os.path.join(parquet_dir, "*.parquet"))
            .repartition(6, "b").groupby("b")
            .agg(col("a").sum().alias("s"), col("g").count().alias("c"))
            .sort("b"))


def _counters(result):
    return result.stats.snapshot()["counters"]


# --------------------------------------------------------------------------
# checksum helpers
# --------------------------------------------------------------------------

class TestChecksumHelpers:
    def test_bytes_and_flip(self):
        from daft_tpu.integrity.checksum import crc32_bytes, \
            flip_payload_bits

        data = b"the quick brown fox" * 100
        assert crc32_bytes(data) == crc32_bytes(bytes(data))
        flipped = flip_payload_bits(data)
        assert flipped != data and len(flipped) == len(data)
        assert crc32_bytes(flipped) != crc32_bytes(data)

    def test_table_checksum_detects_value_change(self):
        import pyarrow as pa

        from daft_tpu.integrity.checksum import crc32_table

        t1 = pa.table({"a": [1, 2, 3], "s": ["x", "y", None]})
        t2 = pa.table({"a": [1, 2, 4], "s": ["x", "y", None]})
        assert crc32_table(t1) == crc32_table(
            pa.table({"a": [1, 2, 3], "s": ["x", "y", None]}))
        assert crc32_table(t1) != crc32_table(t2)

    def test_file_checksum_and_flip(self, tmp_path):
        from daft_tpu.integrity.checksum import crc32_file, flip_file_bits

        p = str(tmp_path / "f.bin")
        with open(p, "wb") as f:
            f.write(b"spilled bytes" * 1000)
        before = crc32_file(p)
        flip_file_bits(p)
        assert crc32_file(p) != before


# --------------------------------------------------------------------------
# spill integrity: {sync, async} x {clean, bit-flip} x {recompute, truncated}
# --------------------------------------------------------------------------

class TestSpillIntegrity:
    @pytest.mark.parametrize("async_spill", [False, True],
                             ids=["sync", "async"])
    def test_clean_spill_byte_identical_zero_recompute(
            self, parquet_dir, async_spill):
        set_execution_config(enable_result_cache=False,
                             scan_tasks_min_size_bytes=1)
        want = _scan_query(parquet_dir).collect().to_arrow()
        set_execution_config(memory_budget_bytes=30_000,
                             async_spill_writes=async_spill)
        r = _scan_query(parquet_dir).collect()
        assert r.to_arrow().equals(want)
        c = _counters(r)
        assert c.get("spilled_partitions", 0) >= 1
        assert c.get("corruption_detected", 0) == 0
        assert c.get("partitions_recomputed", 0) == 0

    @pytest.mark.parametrize("async_spill", [False, True],
                             ids=["sync", "async"])
    def test_bitflip_recomputes_byte_identical(self, parquet_dir,
                                               async_spill):
        set_execution_config(enable_result_cache=False,
                             scan_tasks_min_size_bytes=1)
        want = _scan_query(parquet_dir).collect().to_arrow()
        set_execution_config(memory_budget_bytes=30_000,
                             async_spill_writes=async_spill)
        with faults.inject("spill.corrupt", "always"):
            r = _scan_query(parquet_dir).collect()
        assert r.to_arrow().equals(want)
        c = _counters(r)
        assert c.get("corruption_detected", 0) >= 1
        assert c.get("partitions_recomputed", 0) >= 1
        # exact accounting: every detected corruption was recovered by a
        # recompute, none degraded
        assert c["partitions_recomputed"] >= c["corruption_detected"] \
            or c.get("lineage_truncated", 0) == 0
        rec = r.last_query_record()
        assert rec["outcome"] == "ok"
        assert rec["events"].get("partitions_recomputed", 0) >= 1

    def test_bitflip_covers_encoded_exchange_spill(self, parquet_dir):
        """The spilled-encoded-payload leg: budgeted exchange encodes
        low-cardinality pieces, spills them encoded, and a corrupted
        encoded spill file recomputes through the fanout recipe."""
        set_execution_config(enable_result_cache=False,
                             scan_tasks_min_size_bytes=1)

        def q():
            return (dt.read_parquet(os.path.join(parquet_dir, "*.parquet"))
                    .repartition(6, "g").groupby("g")
                    .agg(col("a").sum().alias("s")).sort("g"))

        want = q().collect().to_arrow()
        set_execution_config(memory_budget_bytes=30_000)
        with faults.inject("spill.corrupt", "always"):
            r = q().collect()
        assert r.to_arrow().equals(want)
        c = _counters(r)
        assert c.get("exchange_pieces_encoded", 0) >= 1
        assert c.get("partitions_recomputed", 0) >= 1

    def test_bitflip_truncated_lineage_degrades_to_daft_error(self):
        """In-memory sources have no stable storage to recompute from:
        corruption degrades to a query-level DaftError (through the
        transient task-retry budget), never a garbled result."""
        set_execution_config(enable_result_cache=False,
                             memory_budget_bytes=20_000)
        df = dt.from_pydict({"a": list(range(60_000)),
                             "b": [i % 7 for i in range(60_000)]})
        q = (df.repartition(6, "b").groupby("b")
             .agg(col("a").sum().alias("s")).sort("b"))
        with faults.inject("spill.corrupt", "always"):
            with pytest.raises(DaftError):
                q.collect()
        rec = dt.query_log()[-1]
        assert rec["outcome"] == "error"
        assert rec["events"].get("lineage_truncated", 0) >= 1

    def test_lineage_log_depth_zero_truncates_even_scan_backed(
            self, parquet_dir):
        set_execution_config(enable_result_cache=False,
                             scan_tasks_min_size_bytes=1,
                             memory_budget_bytes=30_000,
                             lineage_log_depth=0)
        with faults.inject("spill.corrupt", "always"):
            with pytest.raises(DaftError):
                _scan_query(parquet_dir).collect()

    def test_missing_spill_file_recomputes(self):
        """A spill file GONE at unspill (not just corrupt) recovers
        through the same lineage path."""
        from daft_tpu.execution import RuntimeStats
        from daft_tpu.integrity.lineage import LineageLog
        from daft_tpu.micropartition import MicroPartition
        from daft_tpu.spill import MemoryLedger, PartitionBuffer
        from daft_tpu.table import Table

        tbl = Table.from_pydict({"a": list(range(5000))})
        task = _FakeScanTask(tbl)
        part = MicroPartition.from_scan_task(task)
        stats = RuntimeStats()
        buf = PartitionBuffer(1, stats, ledger=MemoryLedger(),
                              integrity=True, lineage=LineageLog())
        buf.append(part)
        spilled = buf.parts()[0]
        assert not spilled.is_loaded()
        os.remove(spilled.scan_task().path)
        out = list(buf.drain())[0].table()
        assert out.to_arrow().equals(tbl.to_arrow())
        assert stats.snapshot()["counters"]["partitions_recomputed"] == 1

    def test_disk_full_classified_and_partial_file_removed(
            self, monkeypatch):
        import daft_tpu.spill as spill_mod
        from daft_tpu.execution import RuntimeStats
        from daft_tpu.micropartition import MicroPartition
        from daft_tpu.spill import MemoryLedger, PartitionBuffer

        written = []

        def enospc_write(path, tbls):
            with open(path, "wb") as f:
                f.write(b"partial")  # the torn write ENOSPC leaves behind
            written.append(path)
            raise OSError(errno.ENOSPC, "No space left on device")

        monkeypatch.setattr(spill_mod, "_write_spill_ipc", enospc_write)
        stats = RuntimeStats()
        ledger = MemoryLedger()
        buf = PartitionBuffer(1, stats, ledger=ledger, integrity=True)
        part = MicroPartition.from_pydict(
            {"a": list(range(4000))})
        buf.append(part)
        c = stats.snapshot()["counters"]
        assert c.get("spill_disk_full", 0) == 1
        assert c.get("spill_write_failures", 0) == 1
        assert ledger.disk_full_events == 1
        assert ledger.snapshot()["disk_full_events"] == 1
        # partial file removed: a later unspill can never read a
        # truncated IPC stream off this slot
        assert written and not os.path.exists(written[0])
        # degraded to hold-in-memory: the data is intact
        out = list(buf.drain())[0]
        assert out.is_loaded() and len(out) == 4000

    def test_integrity_off_skips_checksums_and_counters(self, parquet_dir):
        set_execution_config(enable_result_cache=False,
                             scan_tasks_min_size_bytes=1,
                             partition_integrity=False,
                             lineage_recomputation=False)
        want = _scan_query(parquet_dir).collect().to_arrow()
        set_execution_config(memory_budget_bytes=30_000)
        r = _scan_query(parquet_dir).collect()
        assert r.to_arrow().equals(want)
        c = _counters(r)
        assert c.get("spilled_partitions", 0) >= 1
        assert c.get("corruption_detected", 0) == 0
        assert c.get("partitions_recomputed", 0) == 0
        assert c.get("lineage_truncated", 0) == 0


class _FakeScanTask:
    """Minimal re-readable scan-task surface (stable in-test storage)."""

    def __init__(self, tbl):
        self._tbl = tbl
        self.schema = tbl.schema
        self.stats = None

    @property
    def materialized_schema(self):
        return self._tbl.schema

    def num_rows(self):
        return len(self._tbl)

    def size_bytes(self):
        return self._tbl.size_bytes()

    def read(self):
        return self._tbl

    def read_chunks(self):
        return [self._tbl]

    @property
    def pushdowns(self):
        from daft_tpu.io.scan import Pushdowns

        return Pushdowns()

    def with_pushdowns(self, pd):
        from daft_tpu.spill import _SpillSlotView

        return _SpillSlotView(self, pd)


# --------------------------------------------------------------------------
# encoded exchange payload integrity
# --------------------------------------------------------------------------

class TestEncodedExchangeIntegrity:
    def _encoded(self, integrity=True):
        from daft_tpu.exchange.encode import encode_exchange_partition
        from daft_tpu.micropartition import MicroPartition

        part = MicroPartition.from_pydict(
            {"g": [f"g{i % 4}" for i in range(4000)],
             "a": list(range(4000))})
        enc = encode_exchange_partition(part, integrity=integrity)
        assert enc is not None
        return part, enc

    def test_clean_roundtrip_verified(self):
        part, enc = self._encoded()
        assert enc.scan_task().crc is not None
        assert enc.table().to_arrow().equals(part.table().to_arrow())

    def test_damaged_payload_raises_corruption(self):
        _, enc = self._encoded()
        task = enc.scan_task()
        # simulate in-memory damage: the recorded checksum no longer
        # matches the payload's buffer bytes
        task.crc ^= 0xFF
        with pytest.raises(DaftCorruptionError):
            enc.table()

    def test_integrity_off_no_crc(self):
        part, enc = self._encoded(integrity=False)
        assert enc.scan_task().crc is None
        assert enc.table().to_arrow().equals(part.table().to_arrow())

    def test_crc_covers_dictionary_values(self):
        """DictionaryArray.buffers() omits the dictionary VALUE buffers —
        the actual column data of an encoded piece; the checksum must
        fold them in or value damage decodes silently."""
        import pyarrow as pa

        from daft_tpu.integrity.checksum import crc32_table

        t1 = pa.table({"g": pa.array(["a", "b", "a"]).dictionary_encode()})
        t2 = pa.table({"g": pa.array(["a", "Z", "a"]).dictionary_encode()})
        # identical indices/validity, different dictionary values
        assert crc32_table(t1) != crc32_table(t2)

    def test_encoded_piece_pickles_with_crc(self):
        """Encoded pieces cross process boundaries (dist transport,
        multihost shuffle): the task must pickle — stats stripped, crc
        kept so the receiving process still verifies."""
        import pickle

        from daft_tpu.execution import RuntimeStats
        from daft_tpu.exchange.encode import encode_exchange_partition
        from daft_tpu.micropartition import MicroPartition

        part = MicroPartition.from_pydict(
            {"g": [f"g{i % 4}" for i in range(4000)]})
        enc = encode_exchange_partition(part, stats=RuntimeStats())
        assert enc is not None
        blob = pickle.dumps(enc)
        clone = pickle.loads(blob)
        task = clone.scan_task()
        assert task.crc is not None and task._rt_stats is None
        assert clone.table().to_arrow().equals(part.table().to_arrow())
        # a fresh clone (the first materialization is cached): verify
        # still guards the decode on the receiving side
        tampered = pickle.loads(blob)
        tampered.scan_task().crc ^= 0xFF
        with pytest.raises(DaftCorruptionError):
            tampered.table()


# --------------------------------------------------------------------------
# transport frame integrity
# --------------------------------------------------------------------------

class TestTransportIntegrity:
    def _pair(self):
        a, b = socket.socketpair()
        a.settimeout(5)
        b.settimeout(5)
        return a, b

    def test_clean_roundtrip_checksummed(self):
        from daft_tpu.dist.transport import recv_msg, send_msg

        a, b = self._pair()
        try:
            msg = {"type": "task", "payload": list(range(1000))}
            send_msg(a, msg)
            assert recv_msg(b) == msg
        finally:
            a.close()
            b.close()

    def test_corrupt_frame_raises_corruption_error(self):
        from daft_tpu.dist.transport import recv_msg, send_msg

        a, b = self._pair()
        try:
            with faults.inject("transport.corrupt", "always"):
                send_msg(a, {"type": "task", "payload": b"x" * 4096})
            with pytest.raises(DaftCorruptionError):
                recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_checksum_off_flag_zero_roundtrip(self):
        from daft_tpu.dist.transport import recv_msg, send_msg

        a, b = self._pair()
        try:
            send_msg(a, {"k": 1}, checksum=False)
            assert recv_msg(b) == {"k": 1}
        finally:
            a.close()
            b.close()

    def test_corrupt_frame_e2e_redispatches(self):
        """A corrupted frame on a live worker link reads as a dead link:
        the worker is replaced and the query completes correctly."""
        set_execution_config(enable_result_cache=False,
                             worker_heartbeat_interval_s=0.2)
        df = dt.from_pydict({"a": list(range(20_000)),
                             "b": [i % 9 for i in range(20_000)]})
        q = df.repartition(6).select((col("a") * 2).alias("c")).sort("c")
        want = q.collect().to_arrow()
        set_execution_config(enable_result_cache=False,
                             worker_heartbeat_interval_s=0.2,
                             distributed_workers=2)
        _ = df.repartition(2).select(col("a")).collect()  # warm the pool
        before = sup.worker_pool_snapshot()["worker_losses_total"]
        with faults.inject("transport.corrupt", "first_n", n=1):
            r = q.collect()
            deadline = time.monotonic() + 10
            while (sup.worker_pool_snapshot()["worker_losses_total"]
                   <= before and time.monotonic() < deadline):
                time.sleep(0.1)
        assert r.to_arrow().equals(want)
        assert sup.worker_pool_snapshot()["worker_losses_total"] > before


# --------------------------------------------------------------------------
# speculative straggler mitigation
# --------------------------------------------------------------------------

class TestSpeculation:
    def test_straggler_speculates_first_result_wins_and_off_is_zero(self):
        """One worker slowed via the worker.task delay fault: with
        speculation OFF the counters stay zero; with it ON the straggling
        task gets a duplicate, the fast worker wins, and the result is
        identical."""
        sup.shutdown_worker_pool()  # the env spec binds at spawn
        os.environ[faults.ENV_FAULT_SPEC] = json.dumps(
            {"site": "worker.task", "mode": "always", "delay_s": 0.5,
             "worker_id": 0})
        try:
            def q():
                df = dt.from_pydict({"a": list(range(30_000)),
                                     "b": [i % 9 for i in range(30_000)]})
                return (df.repartition(8)
                        .select((col("a") * 3).alias("c")).sort("c"))

            set_execution_config(enable_result_cache=False,
                                 distributed_workers=0)
            want = q().collect().to_arrow()
            set_execution_config(enable_result_cache=False,
                                 distributed_workers=2,
                                 worker_heartbeat_interval_s=0.2,
                                 speculative_execution=False,
                                 speculation_min_s=0.1,
                                 speculation_quantile_factor=2.0)
            # knob OFF: stragglers stall but never duplicate
            r_off = q().collect()
            assert r_off.to_arrow().equals(want)
            assert _counters(r_off).get("tasks_speculated", 0) == 0
            snap = sup.worker_pool_snapshot()
            assert snap["tasks_speculated_total"] == 0
            # seed the wall history so the p75 threshold is deterministic
            pool = sup._POOL
            with pool._cond:
                for op in list(pool._op_walls) + ["ProjectOp"]:
                    pool._op_walls[op] = deque([0.01] * 8, maxlen=64)
            set_execution_config(enable_result_cache=False,
                                 distributed_workers=2,
                                 worker_heartbeat_interval_s=0.2,
                                 speculative_execution=True,
                                 speculation_min_s=0.1,
                                 speculation_quantile_factor=2.0)
            r_on = q().collect()
            assert r_on.to_arrow().equals(want)
            c = _counters(r_on)
            assert c.get("tasks_speculated", 0) >= 1
            assert c.get("speculation_wins", 0) >= 1
            snap = sup.worker_pool_snapshot()
            assert snap["tasks_speculated_total"] >= 1
            assert snap["speculation_wins_total"] >= 1
            assert snap["speculation_inflight"] == 0
            rec = r_on.last_query_record()
            assert rec["events"].get("tasks_speculated", 0) >= 1
            # health + gauges carry the new cluster counters
            from daft_tpu.obs.health import engine_health, validate_health

            h = engine_health()
            assert validate_health(h) == []
            assert h["cluster"]["tasks_speculated_total"] >= 1
            assert h["cluster"]["speculation_wins_total"] >= 1
            assert "daft_tpu_cluster_speculation_wins_total" \
                in dt.metrics_text()
        finally:
            os.environ.pop(faults.ENV_FAULT_SPEC, None)
            sup.shutdown_worker_pool()

    def test_speculation_bounded_by_max_inflight(self):
        """speculation_max_inflight=0 disables duplicates outright even
        with the knob on — a sick fleet cannot double its own load."""
        sup.shutdown_worker_pool()
        os.environ[faults.ENV_FAULT_SPEC] = json.dumps(
            {"site": "worker.task", "mode": "always", "delay_s": 0.4,
             "worker_id": 0})
        try:
            set_execution_config(enable_result_cache=False,
                                 distributed_workers=2,
                                 worker_heartbeat_interval_s=0.2,
                                 speculative_execution=True,
                                 speculation_min_s=0.05,
                                 speculation_quantile_factor=1.0,
                                 speculation_max_inflight=0)
            df = dt.from_pydict({"a": list(range(10_000))})
            r = df.repartition(4).select((col("a") + 1).alias("c")) \
                .sort("c").collect()
            assert _counters(r).get("tasks_speculated", 0) == 0
        finally:
            os.environ.pop(faults.ENV_FAULT_SPEC, None)
            sup.shutdown_worker_pool()


# --------------------------------------------------------------------------
# cross-process-stable python-object hashing (series.py regression)
# --------------------------------------------------------------------------

class TestStablePythonHash:
    def _hash_values(self):
        from daft_tpu.datatypes import DataType
        from daft_tpu.series import Series

        vals = [object(), {"k": [1, 2]}, ("t", 3), None,
                {"bw", "cx", "dy", "ez"}, frozenset(range(20))]
        s = Series.from_pylist(vals, "v", DataType.python())
        return s.hash().to_pylist()

    def test_none_and_values(self):
        out = self._hash_values()
        assert out[3] is None
        assert all(isinstance(v, int) for v in out[:3] + out[4:])

    def test_equal_containers_hash_equal(self):
        """==-equal sets/dicts must hash equal regardless of iteration
        or insertion order — a plain pickle differs for both (set order
        follows per-process-randomized str hashing; dict order is
        insertion order), which is exactly the mispartitioning hazard."""
        from daft_tpu.datatypes import DataType
        from daft_tpu.series import Series

        d1 = {"a": 1, "b": 2}
        d2 = {}
        d2["b"] = 2
        d2["a"] = 1
        vals = [{"x", "y", "z"}, d1, {"z", "y", "x"}, d2]
        out = Series.from_pylist(
            vals, "v", DataType.python()).hash().to_pylist()
        assert out[0] == out[2]
        assert out[1] == out[3]

    def test_unpicklable_raises_daft_value_error(self):
        import threading

        from daft_tpu.datatypes import DataType
        from daft_tpu.series import Series

        s = Series.from_pylist([threading.Lock()], "v", DataType.python())
        with pytest.raises(DaftValueError):
            s.hash()

    def test_two_process_hash_identical(self):
        """The regression: object()'s default repr embeds the memory
        address, so the old crc32(repr(v)) bucketed the same value
        differently across worker processes — a dist shuffle keyed on
        such a column silently mispartitioned. The stable-pickle hash
        must agree across processes."""
        code = (
            "import os, sys, json\n"
            f"sys.path.insert(0, {ROOT!r})\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "from daft_tpu.datatypes import DataType\n"
            "from daft_tpu.series import Series\n"
            "vals = [object(), {'k': [1, 2]}, ('t', 3), None,\n"
            "        {'bw', 'cx', 'dy', 'ez'}, frozenset(range(20))]\n"
            "s = Series.from_pylist(vals, 'v', DataType.python())\n"
            "print(json.dumps(s.hash().to_pylist()))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.check_output([sys.executable, "-c", code],
                                      env=env, timeout=120)
        remote = json.loads(out.decode().strip().splitlines()[-1])
        assert remote == self._hash_values()


# --------------------------------------------------------------------------
# registry / surfaces
# --------------------------------------------------------------------------

class TestRegistryAndSurfaces:
    def test_new_sites_registered(self):
        for site in ("spill.corrupt", "transport.corrupt", "worker.task"):
            assert site in faults.SITES

    def test_delay_plan_sleeps_instead_of_raising(self):
        faults.arm("test.delay_site", "always", delay_s=0.05)
        try:
            t0 = time.monotonic()
            faults.check("test.delay_site")  # must NOT raise
            assert time.monotonic() - t0 >= 0.04
            assert faults.snapshot()["injected"]["test.delay_site"] == 1
        finally:
            faults.disarm()

    def test_arm_from_env_scopes_by_worker_id(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_FAULT_SPEC, json.dumps(
            {"site": "worker.task", "mode": "always", "worker_id": 3}))
        try:
            assert faults.arm_from_env(worker_id=1) == 0
            assert faults.arm_from_env(worker_id=3) == 1
        finally:
            faults.disarm()

    def test_arm_from_env_malformed_is_noop(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_FAULT_SPEC, "{not json")
        assert faults.arm_from_env(worker_id=0) == 0

    def test_lineage_log_bounds_and_forget(self):
        from daft_tpu.integrity.lineage import LineageLog

        log = LineageLog(depth=2)
        k1 = log.record(lambda: [1])
        k2 = log.record(lambda: [2])
        k3 = log.record(lambda: [3])
        assert log.get(k1) is None  # evicted = truncated lineage
        assert log.get(k2) is not None and log.get(k3) is not None
        assert log.evicted == 1
        log.forget(k2)
        assert log.get(k2) is None
        assert LineageLog(depth=0).record(lambda: []) is None
