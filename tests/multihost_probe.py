"""Minimal raw-collective probe worker: joins an N-process jax cluster and
runs ONE cross-process psum — nothing else. The parent test keeps the
strict xfail for the true ICI-collective gap keyed on this probe's output,
while the engine-level multihost scenarios run for real over the dist/
peer transport.

Run: python multihost_probe.py <process_id> <num_processes> <port>
"""

import os
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from daft_tpu.parallel.multihost import global_mesh, init_distributed  # noqa: E402

assert init_distributed(f"localhost:{port}", nproc, pid)
mesh = global_mesh()

try:
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from daft_tpu.parallel.collectives import _shard_map

    arr = jax.device_put(
        jnp.arange(mesh.devices.size, dtype=jnp.int32),
        NamedSharding(mesh, P(mesh.axis_names[0])))
    probe = _shard_map(
        lambda x: jax.lax.psum(x, mesh.axis_names[0]), mesh=mesh,
        in_specs=P(mesh.axis_names[0]), out_specs=P())
    jax.block_until_ready(probe(arr))
    print(f"PROBE_OK {pid}", flush=True)
except Exception as e:
    print(f"PROBE_FAILED {pid}: {type(e).__name__}: {e}", flush=True)
