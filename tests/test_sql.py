"""SQL frontend tests (reference strategy: tests/sql/test_sql.py — SQL vs
DataFrame-API oracle on the same engine)."""

import datetime

import numpy as np
import pytest

import daft_tpu as dt
from daft_tpu import col, lit, sql, sql_expr


@pytest.fixture
def df():
    return dt.from_pydict({
        "a": [1, 2, 3, 4, 5, None],
        "b": [10.0, 20.0, 30.0, 40.0, 50.0, 60.0],
        "s": ["apple", "banana", "cherry", "date", "apple", None],
        "g": ["x", "y", "x", "y", "x", "y"],
    })


def test_select_where(df):
    out = sql("SELECT a, b * 2 AS b2 FROM t WHERE a > 2", t=df).to_pydict()
    assert out == {"a": [3, 4, 5], "b2": [60.0, 80.0, 100.0]}


def test_select_star_limit(df):
    out = sql("SELECT * FROM t LIMIT 2", t=df).to_pydict()
    assert out["a"] == [1, 2]
    assert set(out) == {"a", "b", "s", "g"}


def test_arith_precedence():
    d = dt.from_pydict({"x": [2, 3]})
    out = sql("SELECT 1 + x * 3 AS y, (1 + x) * 3 AS z FROM t", t=d).to_pydict()
    assert out == {"y": [7, 10], "z": [9, 12]}


def test_groupby_agg_having(df):
    out = sql("""
        SELECT g, SUM(b) AS sb, COUNT(a) AS ca
        FROM t GROUP BY g HAVING SUM(b) > 80 ORDER BY g
    """, t=df).to_pydict()
    assert out == {"g": ["x", "y"], "sb": [90.0, 120.0], "ca": [3, 2]}


def test_compound_agg_expression(df):
    out = sql("SELECT g, SUM(b) / COUNT(b) AS avg_b FROM t GROUP BY g ORDER BY g",
              t=df).to_pydict()
    assert out["avg_b"] == [30.0, 40.0]


def test_count_star(df):
    out = sql("SELECT COUNT(*) FROM t", t=df).to_pydict()
    assert out == {"count": [6]}


def test_global_agg_no_group(df):
    out = sql("SELECT SUM(a) AS s, MAX(b) AS m FROM t", t=df).to_pydict()
    assert out == {"s": [15], "m": [60.0]}


def test_case_when(df):
    out = sql("""
        SELECT a, CASE WHEN a >= 4 THEN 'hi' WHEN a >= 2 THEN 'mid'
                  ELSE 'lo' END AS tier
        FROM t WHERE a IS NOT NULL
    """, t=df).to_pydict()
    assert out["tier"] == ["lo", "mid", "mid", "hi", "hi"]


def test_like_in_between(df):
    out = sql("SELECT s FROM t WHERE s LIKE 'a%'", t=df).to_pydict()
    assert out == {"s": ["apple", "apple"]}
    out = sql("SELECT a FROM t WHERE a IN (1, 3, 5)", t=df).to_pydict()
    assert out == {"a": [1, 3, 5]}
    out = sql("SELECT a FROM t WHERE a BETWEEN 2 AND 4", t=df).to_pydict()
    assert out == {"a": [2, 3, 4]}


def test_string_functions(df):
    out = sql("SELECT UPPER(s) AS u, LENGTH(s) AS l FROM t WHERE s = 'date'",
              t=df).to_pydict()
    assert out == {"u": ["DATE"], "l": [4]}


def test_cast_and_null(df):
    out = sql("SELECT CAST(b AS INT) AS bi, COALESCE(a, 0) AS a0 FROM t LIMIT 6",
              t=df).to_pydict()
    assert out["bi"] == [10, 20, 30, 40, 50, 60]
    assert out["a0"] == [1, 2, 3, 4, 5, 0]


def test_join():
    left = dt.from_pydict({"id": [1, 2, 3], "v": ["a", "b", "c"]})
    right = dt.from_pydict({"rid": [2, 3, 4], "w": [20, 30, 40]})
    out = sql("""
        SELECT l.id, l.v, r.w FROM l JOIN r ON l.id = r.rid ORDER BY id
    """, l=left, r=right).to_pydict()
    assert out == {"id": [2, 3], "v": ["b", "c"], "w": [20, 30]}


def test_left_join_using():
    left = dt.from_pydict({"id": [1, 2, 3], "v": ["a", "b", "c"]})
    right = dt.from_pydict({"id": [2, 3, 4], "w": [20, 30, 40]})
    out = sql("SELECT id, v, w FROM l LEFT JOIN r USING (id) ORDER BY id",
              l=left, r=right).to_pydict()
    assert out == {"id": [1, 2, 3], "v": ["a", "b", "c"], "w": [None, 20, 30]}


def test_subquery():
    d = dt.from_pydict({"x": [1, 2, 3, 4]})
    out = sql("SELECT SUM(x2) AS s FROM (SELECT x * x AS x2 FROM t WHERE x > 1) sq",
              t=d).to_pydict()
    assert out == {"s": [29]}


def test_order_by_desc_nulls(df):
    out = sql("SELECT a FROM t ORDER BY a DESC NULLS LAST", t=df).to_pydict()
    assert out == {"a": [5, 4, 3, 2, 1, None]}


def test_distinct(df):
    out = sql("SELECT DISTINCT g FROM t ORDER BY g", t=df).to_pydict()
    assert out == {"g": ["x", "y"]}


def test_group_by_position_and_alias(df):
    o1 = sql("SELECT g AS grp, SUM(b) AS s FROM t GROUP BY 1 ORDER BY 1", t=df).to_pydict()
    o2 = sql("SELECT g AS grp, SUM(b) AS s FROM t GROUP BY grp ORDER BY grp", t=df).to_pydict()
    assert o1 == o2 == {"grp": ["x", "y"], "s": [90.0, 120.0]}


def test_date_literal():
    d = dt.from_pydict({"d": [datetime.date(2024, 1, 1), datetime.date(2024, 6, 1)]})
    out = sql("SELECT d FROM t WHERE d >= DATE '2024-03-01'", t=d).to_pydict()
    assert out == {"d": [datetime.date(2024, 6, 1)]}


def test_sql_expr_single():
    e = sql_expr("a + 1 > 3 AND b IS NOT NULL")
    d = dt.from_pydict({"a": [1, 3], "b": [1.0, None]})
    assert d.where(e).to_pydict() == {"a": [], "b": []}
    e2 = sql_expr("ABS(a - 4)")
    assert d.select(e2.alias("x")).to_pydict() == {"x": [3, 1]}


def test_tpch_q1_sql_parity():
    from benchmarks import tpch

    tables = tpch.generate_tables(scale=0.002, seed=11)
    li = dt.from_arrow(tables["lineitem"])
    got = sql("""
        SELECT l_returnflag, l_linestatus,
               SUM(l_quantity) AS sum_qty,
               SUM(l_extendedprice) AS sum_base_price,
               SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               AVG(l_quantity) AS avg_qty,
               AVG(l_extendedprice) AS avg_price,
               AVG(l_discount) AS avg_disc,
               COUNT(l_quantity) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """, lineitem=li).to_pydict()
    want = tpch.q1(li).to_pydict()
    assert got.keys() == want.keys()
    for k in want:
        for a, b in zip(got[k], want[k]):
            if isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9)
            else:
                assert a == b


def test_tpch_q6_sql_parity():
    from benchmarks import tpch

    tables = tpch.generate_tables(scale=0.002, seed=11)
    li = dt.from_arrow(tables["lineitem"])
    got = sql("""
        SELECT SUM(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24
    """, lineitem=li).to_pydict()
    want = tpch.q6(li).to_pydict()
    assert got["revenue"][0] == pytest.approx(want["revenue"][0], rel=1e-9)


def test_error_messages(df):
    with pytest.raises(ValueError, match="unknown table"):
        sql("SELECT * FROM missing", t=df)
    with pytest.raises(ValueError, match="GROUP BY"):
        sql("SELECT a, SUM(b) AS s FROM t", t=df)
    with pytest.raises(ValueError, match="expected"):
        sql("SELECT FROM t", t=df)


def test_order_by_column_dropped_by_projection(df):
    # SQL sorts before the projection drops the column
    out = sql("SELECT b FROM t WHERE a IS NOT NULL ORDER BY a DESC", t=df).to_pydict()
    assert out == {"b": [50.0, 40.0, 30.0, 20.0, 10.0]}
    out = sql("SELECT a*a sq FROM t WHERE a IS NOT NULL ORDER BY sq DESC LIMIT 2",
              t=df).to_pydict()
    assert out == {"sq": [25, 16]}


def test_order_by_agg_expression(df):
    out = sql("SELECT g FROM t GROUP BY g ORDER BY SUM(b) DESC", t=df).to_pydict()
    assert out == {"g": ["y", "x"]}


def test_qualified_ref_duplicate_column_after_join():
    # r.v must resolve to the right table's (suffix-renamed) column
    left = dt.from_pydict({"id": [1, 2], "v": ["a", "b"]})
    right = dt.from_pydict({"id": [1, 2], "v": ["X", "Y"]})
    out = sql("SELECT l.id, l.v AS lv, r.v AS rv FROM l JOIN r ON l.id = r.id "
              "ORDER BY 1", l=left, r=right).to_pydict()
    assert out == {"id": [1, 2], "lv": ["a", "b"], "rv": ["X", "Y"]}


def test_qualified_ref_unknown_column_errors():
    left = dt.from_pydict({"id": [1]})
    right = dt.from_pydict({"id": [1]})
    with pytest.raises(ValueError, match="not found in table"):
        sql("SELECT r.nope FROM l JOIN r ON l.id = r.id", l=left, r=right)


def test_chained_comparison_rejected(df):
    with pytest.raises(ValueError, match="chained comparisons"):
        sql("SELECT a FROM t WHERE 1 < a < 3", t=df)


def test_outer_join_non_equi_rejected():
    left = dt.from_pydict({"id": [1, 2], "v": [1, 2]})
    right = dt.from_pydict({"rid": [1, 2], "w": [5, 50]})
    with pytest.raises(ValueError, match="OUTER JOIN"):
        sql("SELECT * FROM l LEFT JOIN r ON l.id = r.rid AND r.w > 40",
            l=left, r=right)


def test_distinct_order_by_sorted():
    d = dt.from_pydict({"x": [9, 1, 9, 3, 1, 7, 3, 5] * 10}).repartition(4)
    out = sql("SELECT DISTINCT x FROM t ORDER BY x", t=d).to_pydict()
    assert out == {"x": [1, 3, 5, 7, 9]}


def test_group_by_input_column_precedence():
    d = dt.from_pydict({"x": [1, 2, 1], "z": [10, 20, 30]})
    out = sql("SELECT -x AS x, SUM(z) AS s FROM t GROUP BY x ORDER BY s",
              t=d).to_pydict()
    # groups by the INPUT column x (SQL precedence), then projects -x
    assert out == {"x": [-2, -1], "s": [20, 40]}
