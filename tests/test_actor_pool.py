"""Actor-pool stateful UDF tests (reference: stateful UDFs + actor pools,
daft/udf.py:308, ActorPoolProject)."""

import threading

import daft_tpu as dt
from daft_tpu import DataType, col
from daft_tpu.actor_pool import _pools, shutdown_all

_init_count = {"n": 0}
_init_lock = threading.Lock()


class Doubler:
    def __init__(self, bias=0):
        with _init_lock:
            _init_count["n"] += 1
        self.bias = bias
        self.calls = 0

    def __call__(self, s):
        self.calls += 1
        return [v * 2 + self.bias for v in s.to_pylist()]


class TestActorPool:
    def setup_method(self):
        shutdown_all()
        _init_count["n"] = 0

    def test_one_instance_per_worker_and_order(self):
        u = dt.udf(return_dtype=DataType.int64())(Doubler).with_concurrency(3)
        df = dt.from_pydict({"x": list(range(100))})
        out = df.select(u(col("x")).alias("y")).to_pydict()
        assert out["y"] == [v * 2 for v in range(100)]  # order preserved
        assert _init_count["n"] == 3  # exactly one init per worker

    def test_pool_reused_across_queries(self):
        u = dt.udf(return_dtype=DataType.int64())(Doubler).with_concurrency(2)
        df = dt.from_pydict({"x": [1, 2, 3, 4]})
        df.select(u(col("x")).alias("y")).to_pydict()
        first = _init_count["n"]
        df.select(u(col("x")).alias("y")).to_pydict()
        assert _init_count["n"] == first  # no re-init on second query

    def test_init_args_separate_pools(self):
        u = dt.udf(return_dtype=DataType.int64())(Doubler)
        u1 = u.with_init_args(bias=100).with_concurrency(2)
        u2 = u.with_init_args(bias=200).with_concurrency(2)
        df = dt.from_pydict({"x": [1, 2]})
        o1 = df.select(u1(col("x")).alias("y")).to_pydict()["y"]
        o2 = df.select(u2(col("x")).alias("y")).to_pydict()["y"]
        assert o1 == [102, 104] and o2 == [202, 204]
        assert len(_pools) == 2

    def test_errors_propagate(self):
        class Boom:
            def __call__(self, s):
                raise RuntimeError("actor failed")

        u = dt.udf(return_dtype=DataType.int64())(Boom).with_concurrency(2)
        df = dt.from_pydict({"x": [1, 2, 3]})
        import pytest

        with pytest.raises(RuntimeError, match="actor failed"):
            df.select(u(col("x")).alias("y")).to_pydict()

    def test_init_failure_raises(self):
        class BadInit:
            def __init__(self):
                raise ValueError("no weights file")

            def __call__(self, s):
                return s

        u = dt.udf(return_dtype=DataType.int64())(BadInit).with_concurrency(2)
        df = dt.from_pydict({"x": [1]})
        import pytest

        with pytest.raises(ValueError, match="no weights file"):
            df.select(u(col("x")).alias("y")).to_pydict()

    def test_stateful_without_concurrency_single_instance(self):
        u = dt.udf(return_dtype=DataType.int64())(Doubler)
        df = dt.from_pydict({"x": [5, 6]})
        out = df.select(u(col("x")).alias("y")).to_pydict()
        assert out["y"] == [10, 12]
        assert _init_count["n"] == 1
