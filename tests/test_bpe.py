"""BPE tokenizer merge-loop regression (ISSUE 3 satellite / VERDICT item 6):
the heap + linked-list merge must match the old quadratic rescan loop
token-for-token and stay fast on long inputs."""

import random
import time

import pytest

from daft_tpu.kernels.bpe import BpeEncoder, get_encoder


def _reference_merge(ranks, piece: bytes):
    """The pre-heap O(n^2) loop, kept as the parity oracle."""
    parts = [piece[i:i + 1] for i in range(len(piece))]
    while len(parts) > 1:
        best_rank, best_i = None, -1
        for i in range(len(parts) - 1):
            r = ranks.get(parts[i] + parts[i + 1])
            if r is not None and (best_rank is None or r < best_rank):
                best_rank, best_i = r, i
        if best_i < 0:
            break
        parts = parts[:best_i] + [parts[best_i] + parts[best_i + 1]] + parts[best_i + 2:]
    return [ranks[p] for p in parts]


@pytest.fixture(scope="module")
def merge_encoder():
    ranks = {bytes([i]): i for i in range(256)}
    nxt = 256
    for w in (b"th", b"the", b"he", b"in", b"ing", b"er", b"an", b"ab",
              b"abc", b"abcd", b" t", b" a", b"qu", b"ui", b"ck", b"ow"):
        if w not in ranks:
            ranks[w] = nxt
            nxt += 1
    return BpeEncoder(ranks)


def test_heap_merge_matches_reference_on_random_inputs(merge_encoder):
    rng = random.Random(42)
    alphabet = b"abcdethinqurckow "
    for _ in range(300):
        s = bytes(rng.choice(alphabet) for _ in range(rng.randint(0, 80)))
        assert merge_encoder._bpe_merge(s) == _reference_merge(
            merge_encoder.ranks, s), s


def test_roundtrip_builtin_bytes_vocab():
    enc = get_encoder("bytes")
    text = "héllo ∑ wörld" * 10
    assert enc.decode(enc.encode(text)) == text


def test_long_input_regression(merge_encoder):
    """40k characters must tokenize in well under a second (the quadratic
    loop took ~25s on the same input)."""
    text = "the quick brown fox jumps over the lazy dog " * 900
    t0 = time.perf_counter()
    out = merge_encoder.encode(text)
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"long-input tokenize took {elapsed:.2f}s"
    assert merge_encoder.decode(out) == text


def test_edge_cases(merge_encoder):
    assert merge_encoder._bpe_merge(b"") == []
    assert merge_encoder._bpe_merge(b"z") == [ord("z")]
    # a piece that fully merges into one multi-byte token
    assert merge_encoder._bpe_merge(b"abcd") == [merge_encoder.ranks[b"abcd"]]
