"""batch-smoke: the dynamic-batching executor validated end to end. Wired
into `make lint` (and usable alone via `make batch-smoke`) so a coalescer,
pinned-actor, or surface regression — a batch that splits a morsel, a model
reloading per query, a gauge going dark, an actor thread leaking past
shutdown — fails the static-gate path before any production consumer
trips over it.

Checks, in order:
 1. COALESCE: a streamed batched-UDF query whose partition splits into 5
    morsels forms ONE batch (whole morsels coalesced across boundaries,
    "end" flush), byte-identical to the same query with the knob off;
 2. BUDGET: the same query under a 2000-row budget forms 3 batches
    (2 budget flushes + 1 end flush), still byte-identical;
 3. TIMER: a Coalescer under an injectable clock flushes the stale run
    with reason "timer" once the oldest buffered morsel exceeds flush_ms;
 4. REUSE: a second query hits the SAME pinned model pool (one
    fingerprint, applies strictly increasing, __init__ ran once);
 5. SURFACES: dt.health()["batching"] validates and the daft_tpu_batch_*
    gauges appear in metrics_text(); the query ledger's batch_inflight
    account settles to zero (no leaked coalesce charge);
 6. SHUTDOWN: dt.shutdown() unpins every model — zero pools, zero
    resident bytes, zero live "daft-actor" threads.

Exits nonzero with a named failure on any violation.
"""

from __future__ import annotations

import os
import sys
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import numpy as np

    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.batch.actors import model_pools_snapshot, pinned_model_count
    from daft_tpu.batch.coalesce import Coalescer
    from daft_tpu.context import get_context
    from daft_tpu.micropartition import MicroPartition
    from daft_tpu.obs.health import validate_health
    from daft_tpu.spill import MEMORY_LEDGER

    cfg = get_context().execution_config
    dt.set_execution_config(streaming_execution=True, dynamic_batching=True,
                            morsel_size_rows=1000, enable_result_cache=False)

    inits = {"n": 0}

    class Scorer:
        weight_bytes = 4096

        def __init__(self):
            inits["n"] += 1

        def __call__(self, v):
            return np.asarray(v.to_numpy(), dtype=np.float64) * 3.0 + 1.0

    # TWO batching declarations over ONE model class: both share the same
    # pinned pool (fingerprint = class + init args + device), so the model
    # loads once no matter how many budgets reference it
    big = dt.batch_udf(return_dtype=dt.DataType.float64(),
                       max_rows=10_000, flush_ms=10_000.0)(Scorer)
    small = dt.batch_udf(return_dtype=dt.DataType.float64(),
                         max_rows=2000, flush_ms=10_000.0)(Scorer)

    data = {"v": [float(i) for i in range(5000)]}

    def run(fn_expr):
        q = dt.from_pydict(data).select(fn_expr.alias("s")).collect()
        return q.to_pydict()["s"], q.stats.snapshot()["counters"]

    try:
        # 1: coalesce across morsels — 5 morsels of 1000 rows, 10k budget
        # => ONE end-flushed batch; byte-identical with the knob off
        got, c1 = run(big(col("v")))
        dt.set_execution_config(dynamic_batching=False)
        want, c_off = run(big(col("v")))
        dt.set_execution_config(dynamic_batching=True)
        if got != want:
            print("batch-smoke: FAIL — batched result differs from knob-off")
            return 1
        if c1.get("batches_formed") != 1 or c1.get("batch_rows") != 5000:
            print(f"batch-smoke: FAIL — expected 1 coalesced batch of 5000 "
                  f"rows, counters: {c1}")
            return 1
        if c1.get("batch_flushes_end") != 1:
            print(f"batch-smoke: FAIL — expected an end flush: {c1}")
            return 1
        if c_off.get("batches_formed"):
            print(f"batch-smoke: FAIL — knob-off run formed batches: {c_off}")
            return 1

        # 2: budget flushes — 2000-row budget over the same 5 morsels
        # => 2 budget flushes + 1 end flush, still byte-identical
        got2, c2 = run(small(col("v")))
        if got2 != want:
            print("batch-smoke: FAIL — budget-flushed result differs")
            return 1
        if c2.get("batches_formed") != 3 \
                or c2.get("batch_flushes_budget") != 2 \
                or c2.get("batch_flushes_end") != 1:
            print(f"batch-smoke: FAIL — wanted 2 budget + 1 end flush: {c2}")
            return 1

        # 3: timer flush under an injectable clock (no wall-clock sleeps)
        now = [0.0]
        co = Coalescer(max_rows=10**9, max_bytes=1 << 40, flush_ms=25.0,
                       clock=lambda: now[0])
        piece = MicroPartition.from_pydict({"x": [1.0, 2.0]})
        if co.feed(piece):
            print("batch-smoke: FAIL — first feed flushed prematurely")
            return 1
        now[0] = 0.050  # 50ms later: oldest exceeds the 25ms deadline
        due = co.feed(piece)
        if len(due) != 1 or due[0].reason != "timer" or due[0].rows != 2:
            print(f"batch-smoke: FAIL — wanted a 2-row timer flush, got "
                  f"{[(f.reason, f.rows) for f in due]}")
            return 1
        tail = co.finish()
        if len(tail) != 1 or tail[0].reason != "end":
            print("batch-smoke: FAIL — finish() did not end-flush the rest")
            return 1

        # 4: actor reuse across queries — same pinned pool, no re-init
        pools = model_pools_snapshot()
        if inits["n"] != 1 or pinned_model_count() != 1:
            # one instance for the one model class, pinned exactly once
            # despite several queries (and two budget declarations) over it
            print(f"batch-smoke: FAIL — wanted 1 pinned model / 1 init, "
                  f"got {pinned_model_count()} pools, {inits['n']} inits")
            return 1
        applies_before = {p["fingerprint"]: p["applies"] for p in pools}
        got3, _ = run(big(col("v")))
        if got3 != want:
            print("batch-smoke: FAIL — warm-actor rerun differs")
            return 1
        if inits["n"] != 1 or pinned_model_count() != 1:
            print(f"batch-smoke: FAIL — rerun re-initialized the model "
                  f"({inits['n']} inits, {pinned_model_count()} pools)")
            return 1
        grew = [p for p in model_pools_snapshot()
                if p["applies"] > applies_before.get(p["fingerprint"], 0)]
        if not grew:
            print("batch-smoke: FAIL — rerun did not go through a pinned "
                  "actor (applies flat)")
            return 1

        # 5: surfaces — health section validates, gauges exported, the
        # coalesce ledger account settled back to zero
        snap = dt.health()
        errs = validate_health(snap)
        if errs:
            print(f"batch-smoke: FAIL — health schema: {errs}")
            return 1
        b = snap["batching"]
        if b["pinned_models"] != 1 or b["batches_formed"] < 4:
            print(f"batch-smoke: FAIL — batching section: {b}")
            return 1
        text = dt.metrics_text()
        for gauge in ("daft_tpu_batch_pinned_models",
                      "daft_tpu_batch_resident_weight_bytes",
                      "daft_tpu_batch_batches_formed_total",
                      "daft_tpu_batch_flushes_budget_total",
                      "daft_tpu_batch_inflight_bytes"):
            if gauge not in text:
                print(f"batch-smoke: FAIL — gauge {gauge} missing")
                return 1
        inflight = MEMORY_LEDGER.snapshot().get("batch_inflight", 0)
        if inflight:
            print(f"batch-smoke: FAIL — batch_inflight leaked {inflight} "
                  "bytes after queries completed")
            return 1
    finally:
        dt.set_execution_config(
            streaming_execution=cfg.streaming_execution,
            dynamic_batching=True,
            morsel_size_rows=cfg.morsel_size_rows,
            enable_result_cache=cfg.enable_result_cache)
        dt.shutdown(timeout_s=5)

    # 6: shutdown unpins everything — no pools, no charge, no threads
    if pinned_model_count() != 0:
        print(f"batch-smoke: FAIL — {pinned_model_count()} model pool(s) "
              "survived dt.shutdown()")
        return 1
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("daft-actor") and t.is_alive()]
    if leaked:
        print(f"batch-smoke: FAIL — leaked actor threads: {leaked}")
        return 1

    print("batch-smoke: OK — cross-morsel coalesce, budget + timer + end "
          "flushes, byte-identity with the knob off, warm pinned actors "
          "across queries, health/gauges, zero leaks after shutdown")
    return 0


if __name__ == "__main__":
    sys.exit(main())
