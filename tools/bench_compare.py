"""Compare the newest BENCH_r*.json against the prior snapshot and flag
per-metric regressions beyond a noise threshold.

The bench snapshots accumulate one JSON per round (BENCH_r01.json,
BENCH_r02.json, ...). This tool diffs the two newest: every numeric metric
present in both is compared with a direction inferred from its name
(walls/latencies/overheads are lower-better; rates/speedups/ratios are
higher-better; unclassifiable metrics are reported as info, never
flagged), and a change WORSE than ``--threshold`` (default 10%, the
observed round-to-round noise on the drifting build hosts) is flagged as
a regression.

Usage:
    python -m tools.bench_compare [--dir DIR] [--threshold 0.10]
                                  [--json] [--strict]

Exit codes: 0 = compared (regressions printed but tolerated), 1 = --strict
and regressions found, 2 = fewer than two snapshots to compare.
`make bench-compare` runs the default form.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_THRESHOLD = 0.10

_SNAPSHOT_RE = re.compile(r"^BENCH_r(\d+)\.json$")

# metric-name suffix -> direction ("lower" = smaller is better). Order
# matters across the two lists: HIGHER is checked first, so the more
# specific "_rows_pruned" (exchange-rung join filters: more pruning is
# better) wins over the generic "_rows" (fewer exchanged rows is better),
# and "_mbps" (throughput, higher) wins over "_peak_mb" (working-set
# peak — lower). A generic "_mb" is deliberately ABSENT: size-context
# keys like streaming_budget_mb/streaming_data_mb track host RAM and
# auto-scaling, not performance, and must stay unclassified so a scale
# flip between rounds is never flagged as a regression.
# Exchanged-payload bytes ("*_exchange_bytes") are lower-better via the
# existing "_bytes" suffix; "_ttfr_s" (time-to-first-row) is listed
# explicitly even though "_s" already covers it — it is a headline
# streaming metric and must survive a reshuffle of the generic suffixes.
# "_recovery_overhead_pct" (distributed rung: the cost of surviving a
# mid-query worker SIGKILL) is headline-pinned the same way, and so is
# "_telemetry_overhead_pct" (distributed rung: what the cluster
# observability plane's per-task fragments cost — the <3% gate). The
# chaos-leg EVENT counts ("_worker_losses", "_task_redispatches",
# "_workers") are deliberately ABSENT from both lists: they are pinned by
# the rung's seeded fault plan, not performance, and a plan change must
# never read as a regression. "_hit_rate" (serving rung: plan-cache hits
# over the repeat-shape leg) is higher-better — a falling hit rate means
# repeat traffic is re-planning. "_preemption_overhead_pct" (distributed
# rung: the cost of gracefully draining a SIGTERMed worker mid-shuffle vs
# an undisturbed run) is headline-pinned like the other overhead gates.
# Driver-payload metrics ("dist_driver_bytes_star"/"dist_driver_bytes_p2p"
# — the p2p flat-in-N gate) are named by LEG, so no fixed suffix covers
# them: classify() special-cases any metric CONTAINING "_driver_bytes" as
# lower-better. The growth RATIOS of that leg end in "_growth_x" and are
# deliberately direction-free: star's growth is expected to track N, and
# a topology change must not read as a perf regression.
_LOWER_SUFFIXES = ("_s", "_ms", "_ns", "_wall_s", "_ttfr_s", "_pct",
                   "_share", "_bytes", "_peak_mb", "_rows",
                   "_misses", "_throttled", "_failures", "_errors",
                   "_overhead_pct", "_recovery_overhead_pct",
                   "_telemetry_overhead_pct", "_preemption_overhead_pct",
                   "_shed_count")
_HIGHER_SUFFIXES = ("_per_sec", "_vs_baseline", "_speedup_x", "_gbps",
                    "_mbps", "_hits", "_qps", "value", "_rows_pruned",
                    "_reduction_x", "_hit_rate", "_fill_pct",
                    "_handoffs_elided", "_warm_x")


def classify(metric: str) -> Optional[str]:
    """'lower' / 'higher' / None (unknown direction — never flagged)."""
    for suf in _HIGHER_SUFFIXES:
        if metric.endswith(suf):
            return "higher"
    for suf in _LOWER_SUFFIXES:
        if metric.endswith(suf):
            return "lower"
    if "_driver_bytes" in metric:
        return "lower"
    return None


def flatten(doc: dict, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a snapshot, nested dicts dotted
    (q1_op_throughput.ScanOp.rows_per_sec ...)."""
    out: Dict[str, float] = {}
    for k, v in doc.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten(v, key + "."))
    return out


def find_snapshots(root: str) -> List[Tuple[int, str]]:
    out = []
    for fn in os.listdir(root):
        m = _SNAPSHOT_RE.match(fn)
        if m:
            out.append((int(m.group(1)), os.path.join(root, fn)))
    return sorted(out)


def compare(prev: dict, new: dict,
            threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Diff two flattened snapshots. Returns {metric: entry} where entry
    carries prev/new/delta_pct/direction/status (regressed | improved |
    stable | info)."""
    p, n = flatten(prev), flatten(new)
    out: Dict[str, dict] = {}
    for metric in sorted(set(p) & set(n)):
        pv, nv = p[metric], n[metric]
        direction = classify(metric)
        if pv == 0:
            delta = 0.0 if nv == 0 else float("inf")
        else:
            delta = (nv - pv) / abs(pv)
        entry = {"prev": pv, "new": nv,
                 "delta_pct": round(delta * 100, 2)
                 if delta != float("inf") else None,
                 "direction": direction}
        if direction is None:
            entry["status"] = "info"
        else:
            worse = delta > threshold if direction == "lower" \
                else delta < -threshold
            better = delta < -threshold if direction == "lower" \
                else delta > threshold
            entry["status"] = ("regressed" if worse
                               else "improved" if better else "stable")
        out[metric] = entry
    return out


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    threshold = DEFAULT_THRESHOLD
    as_json = strict = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--dir":
            i += 1
            root = argv[i]
        elif a.startswith("--dir="):
            root = a.split("=", 1)[1]
        elif a == "--threshold":
            i += 1
            threshold = float(argv[i])
        elif a.startswith("--threshold="):
            threshold = float(a.split("=", 1)[1])
        elif a == "--json":
            as_json = True
        elif a == "--strict":
            strict = True
        else:
            print(f"bench-compare: unknown argument {a!r}", file=sys.stderr)
            return 2
        i += 1
    snaps = find_snapshots(root)
    if len(snaps) < 2:
        print(f"bench-compare: need two BENCH_r*.json under {root}, "
              f"found {len(snaps)}", file=sys.stderr)
        return 2
    (r_prev, p_prev), (r_new, p_new) = snaps[-2], snaps[-1]
    with open(p_prev) as f:
        prev = json.load(f)
    with open(p_new) as f:
        new = json.load(f)
    diff = compare(prev, new, threshold)
    regressions = {m: e for m, e in diff.items()
                   if e["status"] == "regressed"}
    improved = sum(1 for e in diff.values() if e["status"] == "improved")
    if as_json:
        print(json.dumps({
            "prev_round": r_prev, "new_round": r_new,
            "threshold": threshold, "metrics": diff,
            "regressions": sorted(regressions)}, indent=1, sort_keys=True))
    else:
        print(f"bench-compare: r{r_prev:02d} -> r{r_new:02d} "
              f"({len(diff)} shared metric(s), noise ±{threshold:.0%})")
        for m, e in sorted(diff.items()):
            if e["status"] in ("regressed", "improved"):
                arrow = "REGRESSED" if e["status"] == "regressed" else "improved"
                print(f"  {arrow:>9}  {m}: {e['prev']:g} -> {e['new']:g} "
                      f"({e['delta_pct']:+.1f}%)")
        print(f"bench-compare: {len(regressions)} regression(s), "
              f"{improved} improvement(s)")
    return 1 if (strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
