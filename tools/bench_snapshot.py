"""Opportunistic device-bench snapshotter.

The accelerator tunnel is intermittent (wedged for all of round 3's bench
window — BENCH_r03.json: error=tpu_unreachable). This tool decouples
"when the TPU breathes" from "when the driver runs bench.py": run it
periodically during the round; each time the tunnel is alive it executes the
device rungs (same code path as bench.py: parity-gated, device counters
checked) and writes a timestamped BENCH_device_snapshot.json at the repo
root. bench.py falls back to the freshest snapshot when the tunnel is dead
at bench time, so a wedge can no longer erase the whole perf axis.

Usage: python tools/bench_snapshot.py [scale] [--probe-timeout N]
Exit codes: 0 = snapshot written, 2 = tunnel unreachable (no file touched),
1 = device rungs ran but failed (parity/dispatch error recorded in file).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SNAPSHOT = os.path.join(REPO, "BENCH_device_snapshot.json")


def main() -> int:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    scale = float(args[0]) if args else 1.0
    probe_timeout = 180
    for a in sys.argv[1:]:
        if a.startswith("--probe-timeout"):
            probe_timeout = int(a.split("=", 1)[1])

    sys.path.insert(0, REPO)
    import bench

    if not bench._tpu_alive(timeout_s=probe_timeout):
        print("tunnel unreachable; no snapshot", file=sys.stderr)
        return 2

    t_start = time.time()
    out = bench.run_device_rungs(scale)
    out["bench_env"] = bench._bench_env()
    out["snapshot_unix_time"] = round(t_start, 1)
    out["snapshot_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(t_start))
    out["snapshot_wall_s"] = round(time.time() - t_start, 1)

    prev = None
    if os.path.exists(SNAPSHOT):
        try:
            with open(SNAPSHOT) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None

    if not out.get("value") and prev and prev.get("value"):
        # a failed run must never erase an earlier good measurement: keep
        # the good snapshot as the file, annotate the failure on it
        prev["last_failure_utc"] = out["snapshot_utc"]
        prev["last_failure_error"] = out.get("error", "unknown")
        to_write = prev
    else:
        # keep the best previous snapshot's value visible even if this run
        # regressed (the driver wants the round's best honest number)
        if prev and prev.get("value", 0) > out.get("value", 0):
            out["prev_best_value"] = prev["value"]
            out["prev_best_utc"] = prev.get("snapshot_utc")
        to_write = out

    tmp = SNAPSHOT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(to_write, f, indent=1)
    os.replace(tmp, SNAPSHOT)
    print(json.dumps(out))
    return 0 if out.get("value", 0) > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
