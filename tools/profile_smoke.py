"""profile-smoke: run a tiny query with profiling armed and validate every
profiling surface end to end. Wired into `make lint` (and usable alone via
`make profile-smoke`) so a schema regression in the QueryProfile artifact,
the chrome-trace writer, or the metrics dump fails the static-gate path
before any benchmark or downstream tool trips over it.

Checks, in order:
 1. collect(profile=path) produces a QueryProfile that passes
    validate_profile, with ops, a critical path, and zero orphan spans;
 2. the JSON artifact on disk round-trips through validate_profile;
 3. a chrome trace armed around the same query renders span events;
 4. the process metrics registry serves a non-empty Prometheus dump.

Exits nonzero with a named failure on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import daft_tpu as dt
    from daft_tpu import col, tracing
    from daft_tpu.profile import validate_profile

    dt.set_execution_config(enable_result_cache=False)
    tmp = tempfile.mkdtemp(prefix="daft_tpu_profile_smoke_")
    prof_path = os.path.join(tmp, "profile.json")
    trace_path = os.path.join(tmp, "trace.json")

    def query():
        df = dt.from_pydict({"k": ["a", "b", "c"] * 200,
                             "v": list(range(600))})
        return (df.where(col("v") > 3).into_partitions(3)
                .groupby("k").agg(col("v").sum().alias("s")).sort("k"))

    # 1+2: QueryProfile artifact
    q = query().collect(profile=prof_path)
    qp = q.profile()
    if qp is None:
        print("profile-smoke: FAIL — collect(profile=...) built no profile")
        return 1
    errs = validate_profile(qp.to_dict())
    if errs:
        print(f"profile-smoke: FAIL — in-memory schema: {errs}")
        return 1
    if not qp.ops or qp.critical_path_op not in qp.ops:
        print("profile-smoke: FAIL — empty ops/critical path")
        return 1
    if qp.orphan_spans:
        print(f"profile-smoke: FAIL — {qp.orphan_spans} orphan span(s)")
        return 1
    errs = validate_profile(json.load(open(prof_path)))
    if errs:
        print(f"profile-smoke: FAIL — artifact schema: {errs}")
        return 1

    # 3: chrome trace rendered from the span tree
    with tracing.chrome_trace(trace_path):
        query().collect()
    evs = json.load(open(trace_path)).get("traceEvents", [])
    if not any(e.get("ph") == "X" and "span" in e.get("args", {})
               for e in evs):
        print("profile-smoke: FAIL — chrome trace has no span events")
        return 1

    # 4: metrics dump
    text = dt.metrics_text()
    if "daft_tpu_queries_total" not in text:
        print("profile-smoke: FAIL — metrics dump missing queries_total")
        return 1

    print(f"profile-smoke: OK — {len(qp.ops)} op(s), "
          f"critical path {qp.critical_path_op}, "
          f"{len(qp.spans())} span(s), {len(evs)} trace event(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
