"""cache-smoke: the plan/program cache's cold->warm->invalidate->warm
cycle validated end to end. Wired into `make lint` (and usable alone via
`make cache-smoke`) so a keying or invalidation regression — a stale
plan served after a source rewrite, a warm run that silently re-plans,
a gauge surface going dark — fails the static-gate path before any
production consumer trips over it.

Checks, in order:
 1. COLD: the first run of a file-backed query misses the plan cache,
    records planning_wall_ns, and carries both fingerprints (canonical +
    exact) in its QueryRecord;
 2. WARM: the second run hits (zero optimize()/translate() calls, pinned
    by instrumentation), is byte-identical to the cold run, and its
    record shows the same canonical fingerprint;
 3. sub-plan result cache: a second query sharing the scan+project
    prefix replays it (subplan_cache_hits == 1) byte-identically;
 4. INVALIDATE: rewriting the source file (mtime/size change) forces a
    fresh plan AND fresh prefix — the new rows are served, never stale;
 5. WARM AGAIN: the rewritten shape warms back up on its next run;
 6. dt.health()["plan_cache"] validates and the daft_tpu_plan_cache_* /
    daft_tpu_subplan_cache_* gauges appear in metrics_text();
 7. RESTART: two real interpreters share a cache_dir — the first plans
    cold and flushes plan/FDO artifacts, the second serves the same
    shape warm from disk (zero optimize() calls, byte-identical) and
    exports the daft_tpu_persist_* gauges.

Exits nonzero with a named failure on any violation.
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import pyarrow as pa
    import pyarrow.parquet as pq

    import daft_tpu as dt
    import daft_tpu.optimizer as optimizer_mod
    from daft_tpu import col
    from daft_tpu.adapt.plancache import PLAN_CACHE
    from daft_tpu.adapt.resultcache import RESULT_CACHE
    from daft_tpu.obs.health import validate_health

    dt.set_execution_config(enable_result_cache=False)
    PLAN_CACHE.clear()
    RESULT_CACHE.clear()

    calls = {"optimize": 0}
    real_optimize = optimizer_mod.optimize

    def counted(plan, *a, **k):
        calls["optimize"] += 1
        return real_optimize(plan, *a, **k)

    optimizer_mod.optimize = counted
    try:
        d = tempfile.mkdtemp(prefix="cache_smoke_")
        path = os.path.join(d, "t.parquet")
        pq.write_table(pa.table({"k": [i % 5 for i in range(2000)],
                                 "v": [float(i) for i in range(2000)]}),
                       path)

        def query():
            return (dt.read_parquet(path)
                    .with_column("w", col("v") * 2.0)
                    .groupby("k").agg(col("w").sum().alias("s"))
                    .sort("k"))

        # 1: cold
        q1 = query().collect()
        want = q1.to_pydict()
        rec1 = q1.last_query_record()
        if calls["optimize"] != 1:
            print(f"cache-smoke: FAIL — cold run made "
                  f"{calls['optimize']} optimize() calls, wanted 1")
            return 1
        if not rec1 or not rec1["plan_fingerprint_canonical"]:
            print("cache-smoke: FAIL — cold record has no canonical "
                  "fingerprint")
            return 1
        if rec1["planning_ms"] <= 0:
            print("cache-smoke: FAIL — planning_wall_ns not recorded")
            return 1

        # 2: warm — zero re-planning, byte-identical
        q2 = query().collect()
        if calls["optimize"] != 1:
            print(f"cache-smoke: FAIL — warm run re-planned "
                  f"({calls['optimize']} optimize() calls)")
            return 1
        if q2.to_pydict() != want:
            print("cache-smoke: FAIL — warm result differs from cold")
            return 1
        c2 = q2.stats.snapshot()["counters"]
        if c2.get("plan_cache_hits") != 1:
            print(f"cache-smoke: FAIL — warm run counters: {c2}")
            return 1
        rec2 = q2.last_query_record()
        if rec2["plan_fingerprint_canonical"] != \
                rec1["plan_fingerprint_canonical"]:
            print("cache-smoke: FAIL — canonical fingerprint unstable")
            return 1

        # 3: shared prefix replay — same scan+project prefix (identical
        # column pruning), different consumer
        q3 = (dt.read_parquet(path)
              .with_column("w", col("v") * 2.0)
              .groupby("k").agg(col("w").min().alias("m"))
              .sort("k")).collect()
        c3 = q3.stats.snapshot()["counters"]
        if c3.get("subplan_cache_hits", 0) != 1:
            print(f"cache-smoke: FAIL — prefix not replayed: {c3}")
            return 1
        got3 = q3.to_pydict()
        if got3["m"][0] != 0.0 or len(got3["k"]) != 5:
            print(f"cache-smoke: FAIL — replayed prefix wrong result: "
                  f"{got3}")
            return 1

        # 4: source rewrite invalidates both caches (q3's own cold plan
        # made the baseline 2 optimize() calls)
        base = calls["optimize"]
        pq.write_table(pa.table({"k": [1, 1], "v": [100.0, 100.0]}), path)
        q4 = query().collect()
        got4 = q4.to_pydict()
        if got4 != {"k": [1], "s": [400.0]}:
            print(f"cache-smoke: FAIL — stale result after rewrite: "
                  f"{got4}")
            return 1
        if calls["optimize"] != base + 1:
            print(f"cache-smoke: FAIL — rewrite did not force a re-plan "
                  f"({calls['optimize']} optimize() calls, "
                  f"baseline {base})")
            return 1

        # 5: the rewritten shape warms back up
        q5 = query().collect()
        if calls["optimize"] != base + 1 or q5.to_pydict() != got4:
            print("cache-smoke: FAIL — rewritten shape did not re-warm")
            return 1

        # 6: health section + gauges
        snap = dt.health()
        errs = validate_health(snap)
        if errs:
            print(f"cache-smoke: FAIL — health schema: {errs}")
            return 1
        pc = snap["plan_cache"]
        if pc["entries"] < 1 or pc["hits"] < 2 or pc["result_hits"] < 1:
            print(f"cache-smoke: FAIL — plan_cache section: {pc}")
            return 1
        text = dt.metrics_text()
        for gauge in ("daft_tpu_plan_cache_entries",
                      "daft_tpu_plan_cache_hits_total",
                      "daft_tpu_subplan_cache_hits_total"):
            if gauge not in text:
                print(f"cache-smoke: FAIL — gauge {gauge} missing")
                return 1
        # 7: restart warm-start — two fresh interpreters over one
        # cache_dir (daft_tpu/persist/): cold plans + flushes, warm
        # serves with ZERO optimize() calls and identical bytes
        rc = _restart_leg(d)
        if rc:
            return rc
    finally:
        optimizer_mod.optimize = real_optimize
        dt.shutdown(timeout_s=5)

    print("cache-smoke: OK — cold->warm->invalidate->warm cycle, "
          "prefix replay, hit counters, byte-identity, gauges, "
          "restart warm-start")
    return 0


_RESTART_CHILD = r"""
import json, os, sys
sys.path.insert(0, sys.argv[1])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
path, cache_dir = sys.argv[2], sys.argv[3]
import daft_tpu as dt
import daft_tpu.optimizer as optimizer_mod
from daft_tpu import col
dt.set_execution_config(cache_dir=cache_dir)
calls = {"optimize": 0}
real = optimizer_mod.optimize
def counted(plan, *a, **k):
    calls["optimize"] += 1
    return real(plan, *a, **k)
optimizer_mod.optimize = counted
out = (dt.read_parquet(path).with_column("w", col("v") * 2.0)
       .groupby("k").agg(col("w").sum().alias("s")).sort("k")).collect()
got = out.to_pydict()
text = dt.metrics_text()
dt.shutdown(timeout_s=5)
print(json.dumps({"optimize": calls["optimize"], "result": got,
                  "persist_gauges": "daft_tpu_persist_hits_total" in text}))
"""


def _restart_leg(d: str) -> int:
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(d, "restart.parquet")
    cache_dir = os.path.join(d, "restart_cache")
    import pyarrow as pa
    import pyarrow.parquet as pq

    pq.write_table(pa.table({"k": [i % 3 for i in range(500)],
                             "v": [float(i) for i in range(500)]}), path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    runs = []
    for leg in ("cold", "warm"):
        p = subprocess.run([sys.executable, "-c", _RESTART_CHILD,
                            root, path, cache_dir],
                           capture_output=True, text=True, timeout=240,
                           env=env)
        if p.returncode != 0:
            print(f"cache-smoke: FAIL — restart {leg} interpreter died:\n"
                  f"{p.stderr[-2000:]}")
            return 1
        runs.append(json.loads(p.stdout.strip().splitlines()[-1]))
    cold, warm = runs
    if cold["optimize"] < 1:
        print("cache-smoke: FAIL — restart cold leg never planned")
        return 1
    if warm["optimize"] != 0:
        print(f"cache-smoke: FAIL — restart warm leg re-planned "
              f"({warm['optimize']} optimize() calls, wanted 0)")
        return 1
    if warm["result"] != cold["result"]:
        print("cache-smoke: FAIL — restart warm result differs from cold")
        return 1
    if not warm["persist_gauges"]:
        print("cache-smoke: FAIL — daft_tpu_persist_* gauges missing")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
