"""chaos-smoke: a short mixed workload through the distributed runner
under randomized-but-SEEDED worker kills. Wired into `make lint` (and
usable alone via `make chaos-smoke`) so a supervision regression — a
hang, a lost query, a leaked worker process — fails the static-gate path
deterministically (the fault plan hashes (seed, site, call#), so every
run kills the same dispatches).

Checks, in order:
 1. every query in the workload reaches a TERMINAL QueryRecord (outcome
    in the schema's OUTCOMES — recovered "ok" and poison-task "error"
    both count; silence/hang does not), within a hard wall clock;
 2. results of recovered queries are byte-identical to the local runner;
 3. at least one worker loss + re-dispatch actually happened (the chaos
    was real, not a no-op plan);
 4. after shutdown: zero live worker processes, zero engine threads.

Exits nonzero with a named failure on any violation.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS_SEED = 11
KILL_RATE = 0.12
WORKERS = 2
QUERIES = 5


def main() -> int:
    import daft_tpu as dt
    from daft_tpu import col, faults
    from daft_tpu.dist import supervisor as sup
    from daft_tpu.errors import DaftError
    from daft_tpu.obs.querylog import OUTCOMES, validate_record

    dt.set_execution_config(enable_result_cache=False)

    def make_queries():
        df = dt.from_pydict({"a": list(range(4000)),
                             "b": [i % 9 for i in range(4000)]})
        other = dt.from_pydict({"b": list(range(9)),
                                "w": [i * 3 for i in range(9)]})
        return [
            ("map", df.repartition(4).select((col("a") * 2).alias("c"))
             .sort("c")),
            ("agg", df.repartition(4).groupby("b")
             .agg(col("a").sum().alias("s")).sort("b")),
            ("join", df.join(other, on="b").select(col("a"), col("w"))
             .sort("a")),
            ("filter", df.repartition(3).where(col("a") % 7 == 0)
             .select(col("a")).sort("a")),
            ("distinct", df.select(col("b")).distinct().sort("b")),
        ][:QUERIES]

    # oracle results, local runner
    oracle = {name: q.collect().to_arrow() for name, q in make_queries()}

    dt.set_execution_config(distributed_workers=WORKERS,
                            worker_heartbeat_interval_s=0.2)
    # warm the fleet before arming so the chaos hits execution, not spawn
    _ = dt.from_pydict({"a": [1]}).select(col("a")).collect()
    before_log = len(dt.query_log())
    faults.arm("worker.exec", "rate", rate=KILL_RATE, seed=CHAOS_SEED)
    outcomes = {}
    try:
        for name, q in make_queries():
            try:
                res = q.collect()
                rec = res.last_query_record()
                outcomes[name] = (rec, res.to_arrow())
            except DaftError:
                # poison-task (or degraded) failure: terminal, recorded by
                # the flight recorder's finally hook — fetch its record
                outcomes[name] = (dt.query_log()[-1], None)
    finally:
        faults.disarm()

    recs = dt.query_log()[before_log:]
    if len(recs) < QUERIES:
        print(f"FAIL: only {len(recs)} QueryRecords for {QUERIES} queries")
        return 1
    for name, (rec, got) in outcomes.items():
        if rec is None:
            print(f"FAIL: query {name} has no terminal QueryRecord")
            return 1
        errs = validate_record(rec)
        if errs:
            print(f"FAIL: query {name} record invalid: {errs}")
            return 1
        if rec["outcome"] not in OUTCOMES:
            print(f"FAIL: query {name} outcome {rec['outcome']!r}")
            return 1
        if got is not None and not got.equals(oracle[name]):
            print(f"FAIL: query {name} result diverged from local runner")
            return 1
    print(f"CHAOS_QUERIES_OK {len(outcomes)} terminal "
          f"({sum(1 for r, g in outcomes.values() if g is not None)} ok)")

    snap = sup.worker_pool_snapshot()
    if snap is None or snap["worker_losses_total"] < 1:
        print("FAIL: chaos plan never killed a worker — smoke is a no-op")
        return 1
    print(f"CHAOS_LOSSES_OK losses={snap['worker_losses_total']} "
          f"redispatches={snap['task_redispatches_total']} "
          f"restarts={snap['restarts_used']}")

    dt.shutdown()
    live = sup.live_worker_process_count()
    if live:
        print(f"FAIL: {live} worker process(es) leaked after shutdown")
        return 1
    from daft_tpu.serve import leaked_thread_count

    leaked = leaked_thread_count()
    if leaked:
        print(f"FAIL: {leaked} engine thread(s) leaked after shutdown")
        return 1
    print("CHAOS_SHUTDOWN_OK zero leaked processes/threads")
    print("CHAOS_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
