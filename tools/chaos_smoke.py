"""chaos-smoke: a short mixed workload through the distributed runner
under randomized-but-SEEDED chaos. Wired into `make lint` (and usable
alone via `make chaos-smoke`) so a supervision/integrity regression — a
hang, a lost query, a garbled result, a leaked worker process — fails
the static-gate path deterministically (the fault plans hash
(seed, site, call#)).

Three legs, then shutdown:
 1. **worker kills** (``worker.exec`` at rate): every query reaches a
    TERMINAL QueryRecord (recovered "ok" and poison-task "error" both
    count; silence/hang does not), recovered results byte-identical to
    the local runner, at least one real loss + re-dispatch;
 2. **corruption** (``spill.corrupt`` + ``transport.corrupt`` at rate):
    seeded bit-flips on landed spill files and transport frames during a
    budgeted scan-backed workload — every query completes with results
    byte-identical to the clean local runner and at least one partition
    is lineage-recomputed;
 3. **straggler** (one worker slowed via a ``worker.task`` delay plan):
    the query completes within 2x the clean wall (floored at 1s — below
    that the fixed speculation threshold dominates any ratio) with
    ``speculation_wins >= 1``.

After all legs: zero live worker processes, zero engine threads.
Exits nonzero with a named failure on any violation.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS_SEED = 11
KILL_RATE = 0.12
CORRUPT_SPILL_RATE = 0.35
CORRUPT_FRAME_RATE = 0.02
STRAGGLER_DELAY_S = 0.8
WORKERS = 2
QUERIES = 5


def main() -> int:
    import daft_tpu as dt
    from daft_tpu import col, faults
    from daft_tpu.dist import supervisor as sup
    from daft_tpu.errors import DaftError
    from daft_tpu.obs.querylog import OUTCOMES, validate_record

    dt.set_execution_config(enable_result_cache=False)

    def make_queries():
        df = dt.from_pydict({"a": list(range(4000)),
                             "b": [i % 9 for i in range(4000)]})
        other = dt.from_pydict({"b": list(range(9)),
                                "w": [i * 3 for i in range(9)]})
        return [
            ("map", df.repartition(4).select((col("a") * 2).alias("c"))
             .sort("c")),
            ("agg", df.repartition(4).groupby("b")
             .agg(col("a").sum().alias("s")).sort("b")),
            ("join", df.join(other, on="b").select(col("a"), col("w"))
             .sort("a")),
            ("filter", df.repartition(3).where(col("a") % 7 == 0)
             .select(col("a")).sort("a")),
            ("distinct", df.select(col("b")).distinct().sort("b")),
        ][:QUERIES]

    # oracle results, local runner
    oracle = {name: q.collect().to_arrow() for name, q in make_queries()}

    dt.set_execution_config(distributed_workers=WORKERS,
                            worker_heartbeat_interval_s=0.2)
    # warm the fleet before arming so the chaos hits execution, not spawn
    _ = dt.from_pydict({"a": [1]}).select(col("a")).collect()
    before_log = len(dt.query_log())
    faults.arm("worker.exec", "rate", rate=KILL_RATE, seed=CHAOS_SEED)
    outcomes = {}
    try:
        for name, q in make_queries():
            try:
                res = q.collect()
                rec = res.last_query_record()
                outcomes[name] = (rec, res.to_arrow())
            except DaftError:
                # poison-task (or degraded) failure: terminal, recorded by
                # the flight recorder's finally hook — fetch its record
                outcomes[name] = (dt.query_log()[-1], None)
    finally:
        faults.disarm()

    recs = dt.query_log()[before_log:]
    if len(recs) < QUERIES:
        print(f"FAIL: only {len(recs)} QueryRecords for {QUERIES} queries")
        return 1
    for name, (rec, got) in outcomes.items():
        if rec is None:
            print(f"FAIL: query {name} has no terminal QueryRecord")
            return 1
        errs = validate_record(rec)
        if errs:
            print(f"FAIL: query {name} record invalid: {errs}")
            return 1
        if rec["outcome"] not in OUTCOMES:
            print(f"FAIL: query {name} outcome {rec['outcome']!r}")
            return 1
        if got is not None and not got.equals(oracle[name]):
            print(f"FAIL: query {name} result diverged from local runner")
            return 1
    print(f"CHAOS_QUERIES_OK {len(outcomes)} terminal "
          f"({sum(1 for r, g in outcomes.values() if g is not None)} ok)")

    snap = sup.worker_pool_snapshot()
    if snap is None or snap["worker_losses_total"] < 1:
        print("FAIL: chaos plan never killed a worker — smoke is a no-op")
        return 1
    print(f"CHAOS_LOSSES_OK losses={snap['worker_losses_total']} "
          f"redispatches={snap['task_redispatches_total']} "
          f"restarts={snap['restarts_used']}")

    rc = _corruption_leg()
    if rc:
        return rc
    rc = _straggler_leg()
    if rc:
        return rc
    rc = _peer_leg()
    if rc:
        return rc

    dt.shutdown()
    live = sup.live_worker_process_count()
    if live:
        print(f"FAIL: {live} worker process(es) leaked after shutdown")
        return 1
    from daft_tpu.serve import leaked_thread_count

    leaked = leaked_thread_count()
    if leaked:
        print(f"FAIL: {leaked} engine thread(s) leaked after shutdown")
        return 1
    print("CHAOS_SHUTDOWN_OK zero leaked processes/threads")
    print("CHAOS_SMOKE_OK")
    return 0


def _corruption_leg() -> int:
    """Seeded bit-flips on spill files + transport frames: every query
    byte-identical to the clean local runner, >= 1 lineage recompute."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    import daft_tpu as dt
    from daft_tpu import col, faults
    from daft_tpu.errors import DaftError

    d = tempfile.mkdtemp(prefix="chaos_corrupt_src_")

    def make_queries():
        # scan-backed shapes whose spills (fanout/range pieces, encoded
        # exchange payloads, buffered scan partitions) all carry lineage
        # recipes — corruption anywhere on them must self-heal. Shapes
        # that spill post-shuffle loaded partitions (big sorts, join
        # builds) carry truncated lineage BY DESIGN and degrade to a
        # typed error instead; that path is pinned by tests, not smoked.
        df = dt.read_parquet(os.path.join(d, "*.parquet"))
        return [
            ("agg", df.repartition(6, "b").groupby("b")
             .agg(col("a").sum().alias("s")).sort("b")),
            ("agg_enc", df.repartition(6, "g").groupby("g")
             .agg(col("a").sum().alias("s"),
                  col("a").count().alias("c")).sort("g")),
            ("filter", df.repartition(5).where(col("a") % 7 == 0)
             .select(col("a")).sort("a")),
            ("fcount", df.where(col("a") % 3 == 0).repartition(4, "b")
             .groupby("b").agg(col("a").count().alias("c")).sort("b")),
            ("distinct", df.select(col("b"), col("g")).distinct()
             .sort("b")),
        ][:QUERIES]

    try:
        for i in range(4):
            n = 8000
            pq.write_table(pa.table({
                "a": list(range(i * n, (i + 1) * n)),
                "b": [j % 13 for j in range(n)],
                "g": [f"g{j % 5}" for j in range(n)],
            }), os.path.join(d, f"p{i}.parquet"))
        dt.set_execution_config(enable_result_cache=False,
                                scan_tasks_min_size_bytes=1,
                                distributed_workers=0,
                                memory_budget_bytes=None)
        oracle = {name: q.collect().to_arrow()
                  for name, q in make_queries()}
        # star plane pinned (peer_shuffle off): this leg's contract is the
        # DRIVER-side exchange — budgeted bucket spills and driver<->worker
        # frames — whose corruption must lineage-recompute. The peer
        # plane's own loss/corruption recovery is _peer_leg's job.
        dt.set_execution_config(distributed_workers=WORKERS,
                                memory_budget_bytes=120_000,
                                worker_heartbeat_interval_s=0.2,
                                worker_restart_budget=12,
                                peer_shuffle=False)
        _ = dt.from_pydict({"a": [1]}).select(col("a")).collect()  # warm
        before_log = len(dt.query_log())
        faults.arm("spill.corrupt", "rate", rate=CORRUPT_SPILL_RATE,
                   seed=CHAOS_SEED)
        faults.arm("transport.corrupt", "rate", rate=CORRUPT_FRAME_RATE,
                   seed=CHAOS_SEED)
        recomputed = 0
        try:
            for name, q in make_queries():
                try:
                    res = q.collect()
                except DaftError as e:
                    print(f"FAIL: corruption leg query {name} errored: "
                          f"{type(e).__name__}: {str(e)[:120]}")
                    return 1
                if not res.to_arrow().equals(oracle[name]):
                    print(f"FAIL: corruption leg query {name} diverged "
                          "from the clean local runner")
                    return 1
                recomputed += res.stats.snapshot()["counters"].get(
                    "partitions_recomputed", 0)
        finally:
            faults.disarm()
        from daft_tpu.obs.querylog import validate_record

        recs = dt.query_log()[before_log:]
        if len(recs) < QUERIES:
            print(f"FAIL: corruption leg produced {len(recs)} "
                  f"QueryRecords for {QUERIES} queries")
            return 1
        for rec in recs:
            errs = validate_record(rec)
            if errs:
                print(f"FAIL: corruption leg record invalid: {errs}")
                return 1
        if recomputed < 1:
            print("FAIL: corruption leg never recomputed a partition — "
                  "the corruption plan was a no-op")
            return 1
        print(f"CHAOS_CORRUPTION_OK {QUERIES} byte-identical, "
              f"partitions_recomputed={recomputed}")
        return 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _straggler_leg() -> int:
    """One worker slowed via a worker.task delay plan: speculation keeps
    the query within 2x the clean wall (floored) with >= 1 win."""
    import json
    import time
    from collections import deque

    import daft_tpu as dt
    from daft_tpu import col, faults
    from daft_tpu.dist import supervisor as sup

    def q():
        df = dt.from_pydict({"a": list(range(60_000)),
                             "b": [i % 9 for i in range(60_000)]})
        return (df.repartition(8).select((col("a") * 3).alias("c"))
                .sort("c"))

    dt.set_execution_config(enable_result_cache=False,
                            memory_budget_bytes=None,
                            distributed_workers=0)
    want = q().collect().to_arrow()
    # clean distributed wall: fresh pool, no straggler
    sup.shutdown_worker_pool()
    dt.set_execution_config(distributed_workers=WORKERS,
                            worker_heartbeat_interval_s=0.2,
                            speculation_min_s=0.15,
                            speculation_quantile_factor=2.0)
    _ = q().collect()  # spawn + warm
    t0 = time.perf_counter()
    _ = q().collect()
    clean_wall = time.perf_counter() - t0
    # respawn with worker 0 slowed (the env spec binds at spawn)
    sup.shutdown_worker_pool()
    os.environ[faults.ENV_FAULT_SPEC] = json.dumps(
        {"site": "worker.task", "mode": "always",
         "delay_s": STRAGGLER_DELAY_S, "worker_id": 0})
    try:
        _ = q().collect()  # spawn + warm (slowly)
        # seed the wall history so the p75 threshold reflects healthy
        # tasks, not the warmup's straggled ones — deterministic trigger
        pool = sup._POOL
        with pool._cond:
            for op in list(pool._op_walls):
                pool._op_walls[op] = deque([0.01] * 8, maxlen=64)
        t0 = time.perf_counter()
        res = q().collect()
        spec_wall = time.perf_counter() - t0
    finally:
        os.environ.pop(faults.ENV_FAULT_SPEC, None)
    if not res.to_arrow().equals(want):
        print("FAIL: straggler leg result diverged from the local runner")
        return 1
    c = res.stats.snapshot()["counters"]
    snap = sup.worker_pool_snapshot()
    wins = snap["speculation_wins_total"] if snap else 0
    if c.get("speculation_wins", 0) < 1 and wins < 1:
        print("FAIL: straggler leg never won a speculation "
              f"(speculated={c.get('tasks_speculated', 0)})")
        return 1
    limit = 2.0 * max(clean_wall, 1.0)
    if spec_wall > limit:
        print(f"FAIL: straggler leg wall {spec_wall:.2f}s exceeds "
              f"{limit:.2f}s (clean {clean_wall:.2f}s)")
        return 1
    print(f"CHAOS_STRAGGLER_OK wall={spec_wall:.2f}s "
          f"clean={clean_wall:.2f}s wins={wins} "
          f"speculated={c.get('tasks_speculated', 0)}")
    # the next leg / shutdown must not inherit the straggler fleet
    sup.shutdown_worker_pool()
    return 0


def _peer_leg() -> int:
    """Peer-to-peer shuffle plane (ISSUE 16): a scan-backed 5-query
    workload with the seeded ``peer.fetch`` fault killing fetches
    mid-pull, one REAL SIGKILL of a piece-hosting worker mid-query, and
    one graceful drain (SIGTERM path) while the workload runs. Every
    query must come back byte-identical to the clean local runner —
    failed fetches fail over to lineage recompute (``peer_refetches``),
    the drain retires its worker without failing anything
    (``workers_drained``)."""
    import shutil
    import signal
    import tempfile
    import threading
    import time

    import pyarrow as pa
    import pyarrow.parquet as pq

    import daft_tpu as dt
    from daft_tpu import col, faults
    from daft_tpu.dist import supervisor as sup
    from daft_tpu.errors import DaftError
    from daft_tpu.obs.querylog import validate_record

    d = tempfile.mkdtemp(prefix="chaos_peer_src_")

    def make_queries():
        # scan-backed shuffle shapes: their fanouts ship to workers and
        # host pieces remotely (loaded sources stay driver-side by the
        # recomputability rule, so they would not exercise the plane)
        df = dt.read_parquet(os.path.join(d, "*.parquet"))
        other = dt.from_pydict({"b": list(range(13)),
                                "w": [i * 3 for i in range(13)]})
        return [
            ("agg", df.repartition(6, "b").groupby("b")
             .agg(col("a").sum().alias("s")).sort("b")),
            ("rand", df.repartition(5).where(col("a") % 7 == 0)
             .select(col("a")).sort("a")),
            ("join", df.repartition(4, "b").join(other, on="b")
             .select(col("a"), col("w")).sort("a")),
            ("two_stage", df.repartition(6, "g").repartition(4, "b")
             .groupby("b").agg(col("a").count().alias("c")).sort("b")),
            ("distinct", df.repartition(4, "g").select(col("b"), col("g"))
             .distinct().sort("b")),
        ][:QUERIES]

    try:
        for i in range(4):
            n = 8000
            pq.write_table(pa.table({
                "a": list(range(i * n, (i + 1) * n)),
                "b": [j % 13 for j in range(n)],
                "g": [f"g{j % 5}" for j in range(n)],
            }), os.path.join(d, f"p{i}.parquet"))
        dt.set_execution_config(enable_result_cache=False,
                                scan_tasks_min_size_bytes=1,
                                distributed_workers=0,
                                memory_budget_bytes=None)
        oracle = {name: q.collect().to_arrow()
                  for name, q in make_queries()}
        sup.shutdown_worker_pool()
        dt.set_execution_config(distributed_workers=WORKERS,
                                worker_heartbeat_interval_s=0.2,
                                worker_restart_budget=12,
                                peer_shuffle=True)
        _ = dt.from_pydict({"a": [1]}).select(col("a")).collect()  # warm
        pool = sup._POOL
        before_log = len(dt.query_log())
        faults.arm("peer.fetch", "rate", rate=0.25, seed=CHAOS_SEED)
        refetched = drained = 0

        def sigkill_one(after_s):
            time.sleep(after_s)
            with pool._cond:
                pids = [w.proc.pid for w in pool.workers
                        if w.proc is not None and w.state == "ready"]
            if pids:
                try:
                    os.kill(pids[-1], signal.SIGKILL)
                except OSError:
                    pass

        def drain_one(after_s):
            time.sleep(after_s)
            with pool._cond:
                wids = [w.wid for w in pool.workers
                        if w.state == "ready" and not w.draining]
            if wids:
                pool.drain_worker(wids[0])

        try:
            for qi, (name, q) in enumerate(make_queries()):
                chaos = None
                if name == "join":
                    chaos = threading.Thread(target=sigkill_one,
                                             args=(0.05,), daemon=True)
                elif name == "two_stage":
                    chaos = threading.Thread(target=drain_one,
                                             args=(0.05,), daemon=True)
                if chaos is not None:
                    chaos.start()
                try:
                    res = q.collect()
                except DaftError as e:
                    print(f"FAIL: peer leg query {name} errored: "
                          f"{type(e).__name__}: {str(e)[:120]}")
                    return 1
                finally:
                    if chaos is not None:
                        chaos.join()
                if not res.to_arrow().equals(oracle[name]):
                    print(f"FAIL: peer leg query {name} diverged from "
                          "the clean local runner")
                    return 1
                c = res.stats.snapshot()["counters"]
                refetched += c.get("peer_refetches", 0)
        finally:
            faults.disarm()
        recs = dt.query_log()[before_log:]
        if len(recs) < QUERIES:
            print(f"FAIL: peer leg produced {len(recs)} QueryRecords "
                  f"for {QUERIES} queries")
            return 1
        for rec in recs:
            errs = validate_record(rec)
            if errs:
                print(f"FAIL: peer leg record invalid: {errs}")
                return 1
        snap = sup.worker_pool_snapshot()
        drained = snap["workers_drained_total"] if snap else 0
        peer = (snap or {}).get("peer_plane", {})
        if refetched < 1:
            print("FAIL: peer leg never recomputed a piece — the "
                  "peer.fetch plan was a no-op")
            return 1
        if drained < 1:
            print("FAIL: peer leg never drained a worker")
            return 1
        print(f"CHAOS_PEER_OK {QUERIES} byte-identical, "
              f"peer_refetches={refetched} workers_drained={drained} "
              f"pieces_fetched={peer.get('pieces_fetched_total', 0)}")
        sup.shutdown_worker_pool()
        return 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
