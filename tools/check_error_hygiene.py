#!/usr/bin/env python
"""Static error-hygiene pass (wired as a tier-1 test via
tests/test_error_hygiene.py; also runnable standalone: exits nonzero on
violations).

errors.py states an incremental-adoption contract: modules migrated to the
DaftError hierarchy must not regress. For every module in MIGRATED this
pass fails on:

  1. raw builtin raises (``raise ValueError(...)`` and friends) — migrated
     modules raise the typed hierarchy so ``except DaftError`` stays the
     engine-wide catch-all;
  2. bare ``except Exception:`` (or BaseException) whose body is only
     ``pass`` — swallowed failures hide the exact signals the retry layers
     and circuit breaker key on.

Modules are added to MIGRATED as they are migrated; never removed.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

MIGRATED = [
    "daft_tpu/errors.py",
    "daft_tpu/faults.py",
    "daft_tpu/context.py",
    "daft_tpu/expressions.py",
    "daft_tpu/table.py",
    "daft_tpu/io/scan.py",
    "daft_tpu/actor_pool.py",
    "daft_tpu/scheduler.py",
]

# builtin exception constructors a migrated module must not raise raw
# (NotImplementedError is exempt: abstract-method stubs are idiomatic)
RAW_RAISES = {
    "ValueError", "TypeError", "RuntimeError", "Exception", "BaseException",
    "IOError", "OSError", "FileNotFoundError", "PermissionError",
    "KeyError", "IndexError", "ArithmeticError", "ZeroDivisionError",
}

Violation = Tuple[str, int, str]


def check_source(source: str, relpath: str) -> List[Violation]:
    out: List[Violation] = []
    tree = ast.parse(source, filename=relpath)
    for node in ast.walk(tree):
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in RAW_RAISES:
                out.append((relpath, node.lineno,
                            f"raw `raise {name}` — use the DaftError "
                            "hierarchy (daft_tpu/errors.py)"))
        elif isinstance(node, ast.Try):
            for h in node.handlers:
                if not (len(h.body) == 1 and isinstance(h.body[0], ast.Pass)):
                    continue
                label = None
                if h.type is None:  # `except:` — swallows BaseException
                    label = "except:"
                elif (isinstance(h.type, ast.Name)
                        and h.type.id in ("Exception", "BaseException")):
                    label = f"except {h.type.id}:"
                elif isinstance(h.type, ast.Tuple) and any(
                        isinstance(e, ast.Name)
                        and e.id in ("Exception", "BaseException")
                        for e in h.type.elts):
                    label = "except (... Exception ...):"
                if label is not None:
                    out.append((relpath, h.lineno,
                                f"bare `{label} pass` swallows failures the "
                                "retry/breaker layers need to see — handle, "
                                "re-raise typed, or narrow"))
    return out


def run(root: "str | Path | None" = None) -> List[Violation]:
    root = Path(root) if root else Path(__file__).resolve().parent.parent
    violations: List[Violation] = []
    for rel in MIGRATED:
        path = root / rel
        violations.extend(check_source(path.read_text(), rel))
    return violations


def main(argv: List[str]) -> int:
    violations = run(argv[1] if len(argv) > 1 else None)
    for relpath, lineno, msg in violations:
        print(f"{relpath}:{lineno}: {msg}")
    if violations:
        print(f"error hygiene: {len(violations)} violation(s)")
        return 1
    print(f"error hygiene: clean ({len(MIGRATED)} migrated modules)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
