"""Shared interprocedural analysis engine for daftlint.

DTL003/DTL009/DTL010 (and the cross-function half of DTL011) all need the
same substrate: who calls whom, which locks a function acquires, and which
blocking operations it can reach. This module builds that substrate ONCE
per lint run and the rules query it.

The pipeline:

1. **Per-file summaries** (`summarize_file`). A pure-local pass over one
   file's AST producing a JSON-able dict: every function (module
   functions, methods, nested defs — each summarized separately under a
   qualified name like ``WorkerPool._spawn`` or ``main.<locals>.reply``),
   its lock acquisitions (``with self._lock:`` nesting recorded with the
   locks already held), its direct blocking operations (socket IO,
   ``Future.result``, ``queue.get``, ``subprocess``, ``time.sleep``,
   thread joins, semaphore/barrier waits — each with the locks lexically
   held), its call sites (with held locks and receiver shape), its
   MemoryLedger charge/settle calls, plus the file's declared
   synchronization objects (``self.X = threading.Lock()`` …), classes,
   and imports. Because a summary depends only on the file's bytes it is
   cached by content hash (`SummaryCache`) — ``--changed-only`` re-parses
   only edited files.

2. **The model** (`Model`). Joins the summaries: resolves lock
   references to project-wide identities (``ClassName.attr`` for
   instance locks — instances of one class are deliberately conflated,
   the standard approximation for lock-order analysis — and
   ``path::NAME`` for module/closure locks), resolves call sites through
   a tiered scheme (self/cls method -> enclosing class then bases; bare
   name -> nested def, same-module function, ``from``-import, unique
   project function; ``obj.meth`` -> the unique class defining ``meth``,
   with a generic-name blocklist so ``.get``/``.close``/… never create
   false edges), and runs two fixpoints: ``may_block`` (can this
   function reach a blocking operation, with a witness chain) and
   ``transitive_locks`` (locks eventually acquired, with witnesses).

3. **The lock-order graph** (`Model.lock_edges`). ``L -> M`` when some
   function acquires M while holding L, directly or through calls.
   DTL009 reports cycles; DTL010 reports blocking ops/calls whose held
   set is non-empty. Locks declared with a ``# daftlint: io-lock``
   comment are IO-serialization locks (held *by contract* across the one
   stream they serialize, e.g. a per-socket ``send_lock``); DTL010
   skips them, DTL009 still orders them.

Nested ``def`` bodies are summarized with an EMPTY held-lock set (a
closure defined under a lock usually runs later, on another thread — the
opposite choice DTL002 makes lexically, deliberate here to avoid false
blocking-under-lock findings), but their decorators and default
arguments evaluate in the enclosing context and are scanned there.
Lambda bodies are scanned in place (they may well run inline).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import weakref
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .engine import Project, dotted_name

# bump to invalidate every cached summary when the analyzer changes
INTERPROC_VERSION = 1

_LOCKISH = re.compile(r"lock|cond|mutex", re.IGNORECASE)
_QUEUEISH = re.compile(r"queue|(^|_)q$", re.IGNORECASE)
_THREADISH = re.compile(r"thread|(^|_)proc", re.IGNORECASE)
_SEMISH = re.compile(r"sem|slots", re.IGNORECASE)

IO_LOCK_MARK = re.compile(r"#\s*daftlint:\s*io-lock")

# constructor last-segment -> declared kind, for `self.X = threading.Lock()`
_DECL_KINDS = {
    "Lock": "lock", "RLock": "lock", "Condition": "condition",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
    "Event": "event", "Barrier": "barrier",
    "Queue": "queue", "SimpleQueue": "queue", "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "Thread": "thread", "Popen": "process",
    "ThreadPoolExecutor": "executor", "ProcessPoolExecutor": "executor",
}
_LOCK_KINDS = {"lock", "condition"}          # participate in held sets
_WAITABLE_KINDS = {"semaphore", "barrier", "event"}  # acquiring them blocks

# attribute names too generic to resolve through "the unique class that
# defines this method" — without this list, `self._pieces.get(...)` would
# resolve to some project class's `get` and fabricate call edges
GENERIC_METHODS = frozenset({
    "get", "put", "pop", "popleft", "popitem", "close", "join", "start",
    "stop", "run", "send", "recv", "wait", "acquire", "release", "notify",
    "notify_all", "set", "clear", "get_nowait", "put_nowait", "items",
    "keys", "values", "append", "appendleft", "extend", "add", "discard",
    "remove", "update", "copy", "read", "write", "flush", "seek", "tell",
    "result", "cancel", "done", "submit", "map", "shutdown", "poll",
    "kill", "terminate", "encode", "decode", "strip", "split", "format",
    "lower", "upper", "replace", "count", "index", "sort", "reverse",
    "insert", "search", "match", "sub", "group", "setdefault", "name",
    "exists", "mkdir", "touch", "snapshot", "check", "bump",
    # stdlib logging.Logger methods that collide with project classes
    # (py_logger.exception(...) must not resolve to QueryHandle.exception)
    "exception", "log",
})

_SOCKET_METHODS = {"accept", "recv", "recv_into", "recvfrom", "sendall",
                   "connect", "connect_ex", "makefile"}
_SOCKISH = re.compile(r"sock|conn|cand|listener|peer", re.IGNORECASE)

# MemoryLedger charge -> the settle method(s) that balance it
LEDGER_PAIRS: Dict[str, Tuple[str, ...]] = {
    "prefetch_started": ("prefetch_done",),
    "stream_started": ("stream_done",),
    "exec_started": ("exec_done",),
    "dist_started": ("dist_done",),
    "async_spill_started": ("async_spill_done", "async_spill_abandoned",
                            "async_spill_failed"),
}
LEDGER_SETTLES = frozenset(m for ms in LEDGER_PAIRS.values() for m in ms)
LEDGER_METHODS = frozenset(LEDGER_PAIRS) | LEDGER_SETTLES


# ---------------------------------------------------------------------------
# per-file summarization (pure function of one file's source)
# ---------------------------------------------------------------------------

def _recv_of(func: ast.Attribute) -> str:
    """Receiver shape for an attribute call: 'self'/'cls', a dotted name
    ('time', 'entry.ctx.ledger'), or '?' for computed receivers."""
    base = func.value
    d = dotted_name(base)
    if d is not None:
        return d
    return "?"


def _static_str_prefix(node: ast.AST) -> Optional[str]:
    """The static leading text of a string literal or f-string, or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                out.append(part.value)
            else:
                break
        return "".join(out)
    return None


class _FileSummarizer:
    """One pass over one file. Produces the JSON-able file summary."""

    def __init__(self, rel: str, source: str, tree: ast.Module):
        self.rel = rel
        self.lines = source.splitlines()
        self.tree = tree
        self.types: Dict[str, str] = {}      # "class:C.X"/"module:X"/"local:q:X" -> kind
        self.io_locks: List[str] = []        # resolved lock ids marked io-lock
        self.classes: Dict[str, dict] = {}   # C -> {"methods": [...], "bases": [...]}
        self.imports: Dict[str, str] = {}    # alias -> absolute module
        self.from_imports: Dict[str, List[str]] = {}  # name -> [module, orig]
        self.functions: Dict[str, dict] = {}  # qual -> function summary
        parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        self.module = ".".join(parts)
        self.package = ".".join(parts[:-1])

    def run(self) -> dict:
        self._collect_decls(self.tree, cls=None, qual=None)
        self._walk_module()
        return {"path": self.rel, "types": self.types,
                "io_locks": sorted(set(self.io_locks)),
                "classes": self.classes, "imports": self.imports,
                "from_imports": self.from_imports,
                "functions": self.functions}

    # ---- pass A: declarations (types, classes, imports) -------------------

    def _collect_decls(self, node: ast.AST, cls: Optional[str],
                       qual: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                bases = [dotted_name(b) or "" for b in child.bases]
                self.classes.setdefault(child.name, {
                    "methods": [], "bases": [b.split(".")[-1]
                                             for b in bases if b]})
                for item in child.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.classes[child.name]["methods"].append(item.name)
                self._collect_decls(child, child.name, qual)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = (f"{qual}.<locals>.{child.name}" if qual
                     else (f"{cls}.{child.name}" if cls else child.name))
                self._collect_decls(child, cls, q)
            elif isinstance(child, ast.Import):
                for a in child.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(child, ast.ImportFrom):
                base = child.module or ""
                if child.level:
                    up = self.package.split(".") if self.package else []
                    up = up[: len(up) - (child.level - 1)]
                    base = ".".join(up + ([child.module]
                                          if child.module else []))
                for a in child.names:
                    self.from_imports[a.asname or a.name] = [base, a.name]
                self._collect_decls(child, cls, qual)
            else:
                self._maybe_decl(child, cls, qual)
                self._collect_decls(child, cls, qual)

    def _maybe_decl(self, node: ast.AST, cls: Optional[str],
                    qual: Optional[str]) -> None:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            return
        value = node.value
        if not isinstance(value, ast.Call):
            return
        ctor = dotted_name(value.func)
        if ctor is None:
            return
        kind = _DECL_KINDS.get(ctor.split(".")[-1])
        if kind is None:
            return
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            key = lock_id = None
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self" and cls is not None):
                key, lock_id = f"class:{cls}.{tgt.attr}", f"{cls}.{tgt.attr}"
            elif isinstance(tgt, ast.Name):
                if qual is None:
                    key = f"module:{tgt.id}"
                    lock_id = f"{self.rel}::{tgt.id}"
                else:
                    key = f"local:{qual}:{tgt.id}"
                    lock_id = f"{self.rel}::{qual}.{tgt.id}"
            if key is None:
                continue
            self.types[key] = kind
            line = self.lines[node.lineno - 1] if (
                0 < node.lineno <= len(self.lines)) else ""
            if kind in _LOCK_KINDS and IO_LOCK_MARK.search(line):
                self.io_locks.append(lock_id)

    # ---- pass B: function walks ------------------------------------------

    def _walk_module(self) -> None:
        mod = self._new_fn("<module>", None, 1)
        self._walk_stmts(self.tree.body, mod, cls=None, held=())
        self.functions["<module>"] = mod

    def _new_fn(self, qual: str, cls: Optional[str], line: int) -> dict:
        name = qual.split("#")[0].split(".")[-1]
        # top-level bare name, the grouping DTL003 keys its call graph by:
        # "C.m" and "C.m.<locals>.g" both belong to top-level function "m"
        head = qual.split(".<locals>.")[0].split("#")[0]
        top = None if head == "<module>" else head.split(".")[-1]
        return {"qual": qual, "name": name, "cls": cls, "top": top,
                "line": line, "acquires": [], "blocking": [], "calls": [],
                "ledger": [], "guard": False, "collectives": []}

    def _unique_qual(self, qual: str) -> str:
        if qual not in self.functions:
            return qual
        i = 2
        while f"{qual}#{i}" in self.functions:
            i += 1
        return f"{qual}#{i}"

    def _walk_fn(self, node: ast.AST, qual: str, cls: Optional[str]) -> None:
        fsum = self._new_fn(qual, cls, node.lineno)
        self.functions[qual] = fsum
        self._walk_stmts(node.body, fsum, cls, held=())

    def _walk_stmts(self, stmts: Sequence[ast.stmt], fsum: dict,
                    cls: Optional[str], held: Tuple[str, ...]) -> None:
        prev: Optional[ast.stmt] = None
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # decorators/defaults evaluate HERE, in the current context
                for dec in stmt.decorator_list:
                    self._scan_expr(dec, fsum, cls, held)
                for d in list(stmt.args.defaults) + [
                        d for d in stmt.args.kw_defaults if d is not None]:
                    self._scan_expr(d, fsum, cls, held)
                q = self._nested_qual(fsum, cls, stmt.name)
                self._walk_fn(stmt, q, cls=None)
            elif isinstance(stmt, ast.ClassDef):
                for dec in stmt.decorator_list:
                    self._scan_expr(dec, fsum, cls, held)
                inner_cls = stmt.name
                body_rest = []
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        if fsum["qual"] == "<module>":
                            q = self._unique_qual(f"{inner_cls}.{item.name}")
                        else:
                            q = self._unique_qual(
                                f"{fsum['qual']}.<locals>."
                                f"{inner_cls}.{item.name}")
                        for dec in item.decorator_list:
                            self._scan_expr(dec, fsum, cls, held)
                        self._walk_fn(item, q, cls=inner_cls)
                    else:
                        body_rest.append(item)
                # non-method class-body statements execute at class
                # creation time, i.e. in the current context
                self._walk_stmts(body_rest, fsum, inner_cls, held)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    ref = self._sync_ref(item.context_expr, fsum, cls)
                    if ref is not None:
                        fsum["acquires"].append(
                            {"ref": ref, "line": item.context_expr.lineno,
                             "held": list(new_held), "try": False})
                        new_held = new_held + (ref,)
                    else:
                        self._scan_expr(item.context_expr, fsum, cls, held)
                self._walk_stmts(stmt.body, fsum, cls, new_held)
            elif isinstance(stmt, ast.Try):
                # the canonical explicit-hold idiom: `X.acquire()` as the
                # last statement before `try: ... finally: X.release()` —
                # treat the try body as running under X (DTL010 would
                # otherwise be blind to non-`with` lock holds)
                extra = self._finally_released(prev, stmt, fsum, cls)
                if extra is None and stmt.body:
                    # variant: the acquire is the try's FIRST statement
                    extra = self._finally_released(stmt.body[0], stmt,
                                                   fsum, cls)
                h2 = held + ((extra,) if extra else ())
                self._walk_stmts(stmt.body, fsum, cls, h2)
                for h in stmt.handlers:
                    self._walk_stmts(h.body, fsum, cls, h2)
                self._walk_stmts(stmt.orelse, fsum, cls, h2)
                self._walk_stmts(stmt.finalbody, fsum, cls, held)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(stmt.test, fsum, cls, held)
                self._walk_stmts(stmt.body, fsum, cls, held)
                self._walk_stmts(stmt.orelse, fsum, cls, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter, fsum, cls, held)
                self._walk_stmts(stmt.body, fsum, cls, held)
                self._walk_stmts(stmt.orelse, fsum, cls, held)
            elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
                self._scan_expr(stmt.subject, fsum, cls, held)
                for case in stmt.cases:
                    self._walk_stmts(case.body, fsum, cls, held)
            else:
                self._scan_expr(stmt, fsum, cls, held)
            prev = stmt

    def _finally_released(self, prev: Optional[ast.stmt], try_stmt: ast.Try,
                          fsum: dict, cls: Optional[str]) -> Optional[str]:
        """The sync ref R when `prev` is `R.acquire()` and the try's
        finally contains `R.release()` — the explicit-hold idiom."""
        if (not isinstance(prev, ast.Expr)
                or not isinstance(prev.value, ast.Call)):
            return None
        call = prev.value
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"):
            return None
        ref = self._sync_ref(call.func.value, fsum, cls)
        if ref is None:
            return None
        for fin in try_stmt.finalbody:
            for n in ast.walk(fin):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "release"
                        and self._sync_ref(n.func.value, fsum, cls) == ref):
                    return ref
        return None

    def _nested_qual(self, fsum: dict, cls: Optional[str],
                     name: str) -> str:
        if fsum["qual"] == "<module>":
            return self._unique_qual(f"{cls}.{name}" if cls else name)
        return self._unique_qual(f"{fsum['qual']}.<locals>.{name}")

    # ---- expression scan: calls, blocking ops, locks, ledger --------------

    def _scan_expr(self, node: ast.AST, fsum: dict, cls: Optional[str],
                   held: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue  # statement walk owns these
            self._scan_expr(child, fsum, cls, held)
        if isinstance(node, ast.Call):
            self._classify_call(node, fsum, cls, held)

    def _sync_ref(self, expr: ast.AST, fsum: dict,
                  cls: Optional[str]) -> Optional[str]:
        """Raw reference string when `expr` names a synchronization object:
        's:attr' (self.attr), 'n:name' (bare name), 'a:attr' (attr on some
        other receiver). None when `expr` isn't lockish/declared."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            if cls is not None and (
                    f"class:{cls}.{expr.attr}" in self.types
                    or _LOCKISH.search(expr.attr)):
                return f"s:{expr.attr}"
            if _LOCKISH.search(expr.attr):
                return f"s:{expr.attr}"
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            if (f"module:{name}" in self.types or _LOCKISH.search(name)
                    or self._local_type(fsum["qual"], name) is not None):
                return f"n:{name}"
            return None
        if isinstance(expr, ast.Attribute):
            if _LOCKISH.search(expr.attr):
                return f"a:{expr.attr}"
            return None
        return None

    def _local_type(self, qual: str, name: str) -> Optional[str]:
        """Declared kind for a function-local name, walking enclosing
        function scopes (closures see outer locals)."""
        parts = qual.split(".<locals>.")
        while parts:
            q = ".<locals>.".join(parts)
            kind = self.types.get(f"local:{q}:{name}")
            if kind is not None:
                return kind
            parts.pop()
        return None

    def _recv_kind(self, func: ast.Attribute, fsum: dict,
                   cls: Optional[str]) -> Optional[str]:
        """Declared kind of an attribute call's receiver, when the file
        declares it (self.X / module X / local X / unique class attr is
        resolved later at the model level)."""
        base = func.value
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and cls is not None):
            return self.types.get(f"class:{cls}.{base.attr}")
        if isinstance(base, ast.Name):
            k = self._local_type(fsum["qual"], base.id)
            if k is not None:
                return k
            return self.types.get(f"module:{base.id}")
        return None

    def _classify_call(self, node: ast.Call, fsum: dict,
                       cls: Optional[str], held: Tuple[str, ...]) -> None:
        func = node.func
        dotted = dotted_name(func)
        line = node.lineno

        # DTL003 facts: collectives and breaker guards
        cname = _collective_call(node)
        if cname is not None:
            fsum["collectives"].append(
                [cname, line, _has_axis(node)])
        if isinstance(func, ast.Attribute) and func.attr == "allow":
            fsum["guard"] = True

        blocked = self._maybe_blocking(node, func, dotted, fsum, cls, held)
        if blocked:
            return

        # ledger charge/settle calls (receiver checked by the rule)
        if (isinstance(func, ast.Attribute)
                and func.attr in LEDGER_METHODS):
            fsum["ledger"].append({"meth": func.attr, "line": line})

        # plain call site
        if isinstance(func, ast.Name):
            fsum["calls"].append({"name": func.id, "recv": "", "line": line,
                                  "held": list(held)})
        elif isinstance(func, ast.Attribute):
            fsum["calls"].append({"name": func.attr,
                                  "recv": _recv_of(func), "line": line,
                                  "held": list(held)})

    def _maybe_blocking(self, node: ast.Call, func: ast.AST,
                        dotted: Optional[str], fsum: dict,
                        cls: Optional[str],
                        held: Tuple[str, ...]) -> bool:
        """Record a direct blocking operation; True when classified."""

        def block(kind: str, released: Optional[str] = None) -> bool:
            fsum["blocking"].append(
                {"kind": kind, "line": node.lineno, "held": list(held),
                 "rel": released})
            return True

        if dotted == "time.sleep":
            return block("time.sleep")
        if dotted == "open":
            return block("file io (open)")
        if dotted in ("os.fsync", "os.read", "os.write"):
            return block(f"file io ({dotted})")
        if dotted is not None and dotted.startswith("subprocess."):
            if dotted.split(".")[-1] in ("run", "call", "check_call",
                                         "check_output", "Popen"):
                return block(f"subprocess ({dotted})")
        if dotted in ("select.select", "selectors.select"):
            return block("select")
        if not isinstance(func, ast.Attribute):
            return False

        attr, recv = func.attr, _recv_of(func)
        recv_last = recv.split(".")[-1]
        rkind = self._recv_kind(func, fsum, cls)

        if attr in _SOCKET_METHODS:
            return block(f"socket.{attr}")
        if attr == "send" and _SOCKISH.search(recv_last):
            return block("socket.send")
        if attr == "communicate":
            return block("subprocess (communicate)")
        if attr == "result":
            return block("future.result")
        if attr in ("wait", "wait_for"):
            # a Condition.wait on a HELD condition releases it for the
            # duration — the whitelist is applied at the model level by
            # matching `rel` against the resolved held set
            rel = self._sync_ref(func.value, fsum, cls)
            return block(f"wait ({recv}.{attr})", released=rel)
        if attr == "get":
            positional = [a for a in node.args
                          if not isinstance(a, ast.Starred)]
            nonblock = any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords)
            if (not positional and not nonblock
                    and (rkind == "queue" or _QUEUEISH.search(recv_last))):
                return block("queue.get")
            return False
        if attr == "join":
            if isinstance(func.value, ast.Constant):
                return False  # ", ".join(...)
            if recv in ("os.path", "posixpath", "STORAGE"):
                return False
            if (rkind in ("thread", "process", "executor")
                    or _THREADISH.search(recv_last)
                    or recv in ("t", "th")):
                return block("thread.join")
            return False
        if attr == "acquire":
            nonblock = any(isinstance(a, ast.Constant) and a.value is False
                           for a in node.args) or any(
                kw.arg == "blocking" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords)
            ref = self._sync_ref(func.value, fsum, cls)
            if rkind == "semaphore" or (
                    ref is None and _SEMISH.search(recv_last)):
                if nonblock:
                    return True  # try-acquire: neither blocking nor a lock
                return block("semaphore.acquire")
            if ref is not None:
                # explicit lock acquisition: an ordering event, not a
                # blocking op (DTL009's territory); held-ness past this
                # statement is not tracked (flow-insensitive)
                fsum["acquires"].append(
                    {"ref": ref, "line": node.lineno, "held": list(held),
                     "try": nonblock})
                return True
            return False
        return False


# DTL003's collective matchers live here so summaries carry the facts
COLLECTIVES = {"all_to_all", "psum", "pmax", "pmin", "pmean", "all_gather",
               "ppermute", "pshuffle", "pbroadcast", "psum_scatter"}
_AXIS_KEYWORDS = {"axis_name", "axis"}


def _collective_call(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[-1] in COLLECTIVES and (
            len(parts) == 1 or parts[-2] == "lax"
            or parts[0] in ("jax", "lax")):
        return name
    return None


def _has_axis(node: ast.Call) -> bool:
    if len(node.args) >= 2:
        return True
    return any(kw.arg in _AXIS_KEYWORDS for kw in node.keywords)


def summarize_file(rel: str, source: str,
                   tree: Optional[ast.Module]) -> dict:
    if tree is None:
        return {"path": rel, "types": {}, "io_locks": [], "classes": {},
                "imports": {}, "from_imports": {}, "functions": {}}
    return _FileSummarizer(rel, source, tree).run()


# ---------------------------------------------------------------------------
# summary cache (content-hash keyed, used by --changed-only)
# ---------------------------------------------------------------------------

def source_digest(source: str) -> str:
    return hashlib.sha1(source.encode("utf-8")).hexdigest()


class SummaryCache:
    """Per-file summaries keyed by content hash, persisted as one JSON
    file. A version stamp invalidates everything when the analyzer's
    summary shape changes."""

    def __init__(self, path: str):
        self.path = path
        self._files: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if data.get("interproc") == INTERPROC_VERSION:
                self._files = data.get("files", {})
        except (OSError, ValueError):
            self._files = {}

    def get(self, rel: str, digest: str) -> Optional[dict]:
        entry = self._files.get(rel)
        if entry is not None and entry.get("sha") == digest:
            self.hits += 1
            return entry["summary"]
        self.misses += 1
        return None

    def put(self, rel: str, digest: str, summary: dict) -> None:
        self._files[rel] = {"sha": digest, "summary": summary}

    def save(self) -> None:
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"interproc": INTERPROC_VERSION,
                           "files": self._files}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a cache that cannot persist is only a slower cache


# ---------------------------------------------------------------------------
# the joined model
# ---------------------------------------------------------------------------

class Model:
    """Project-wide view over the per-file summaries. All resolution and
    fixpoint state is computed eagerly in __init__ (the summaries are the
    expensive part; the joins are linear)."""

    def __init__(self, project: Project, summaries: Dict[str, dict]):
        self.project = project
        self.summaries = summaries
        # indexes
        self.functions: Dict[str, dict] = {}      # "rel::qual" -> fsum
        self.file_of: Dict[str, str] = {}         # key -> rel
        self.class_file: Dict[str, str] = {}
        self.class_info: Dict[str, dict] = {}
        self.attr_kind: Dict[str, str] = {}       # "C.attr" -> kind
        self.attr_classes: Dict[str, List[str]] = {}   # sync attr -> classes
        self.method_classes: Dict[str, List[str]] = {}  # meth -> classes
        self.module_file: Dict[str, str] = {}
        self.io_locks: Set[str] = set()
        self.module_fns: Dict[str, List[str]] = {}  # bare -> [keys]
        for rel in sorted(summaries):
            s = summaries[rel]
            mod = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            self.module_file[mod] = rel
            for cname, info in s["classes"].items():
                self.class_file.setdefault(cname, rel)
                self.class_info.setdefault(cname, info)
                for m in info["methods"]:
                    self.method_classes.setdefault(m, [])
                    if cname not in self.method_classes[m]:
                        self.method_classes[m].append(cname)
            for tkey, kind in s["types"].items():
                if tkey.startswith("class:"):
                    ca = tkey[len("class:"):]
                    self.attr_kind.setdefault(ca, kind)
                    attr = ca.split(".", 1)[1]
                    self.attr_classes.setdefault(attr, [])
                    cls = ca.split(".", 1)[0]
                    if cls not in self.attr_classes[attr]:
                        self.attr_classes[attr].append(cls)
            self.io_locks.update(s["io_locks"])
            for qual, fsum in s["functions"].items():
                key = f"{rel}::{qual}"
                self.functions[key] = fsum
                self.file_of[key] = rel
                if "." not in qual and qual != "<module>":
                    self.module_fns.setdefault(qual, []).append(key)
        self._resolve_cache: Dict[Tuple[str, str, str, Optional[str]],
                                  Optional[Tuple[str, str]]] = {}
        self._compute_flow()

    # ---- lock reference resolution ---------------------------------------

    def resolve_lock(self, ref: str, rel: str,
                     fsum: dict) -> Optional[Tuple[str, str]]:
        """(lock_id, kind) for a raw 's:'/'n:'/'a:' reference, or None.
        kind is a declared kind, or 'lock' for lockish-named undeclareds."""
        ck = (ref, rel, fsum["qual"], fsum["cls"])
        if ck in self._resolve_cache:
            return self._resolve_cache[ck]
        out = self._resolve_lock_uncached(ref, rel, fsum)
        self._resolve_cache[ck] = out
        return out

    def _resolve_lock_uncached(self, ref: str, rel: str,
                               fsum: dict) -> Optional[Tuple[str, str]]:
        tag, name = ref.split(":", 1)
        s = self.summaries[rel]
        if tag == "s":
            cls = fsum["cls"]
            if cls is None:
                return None
            c = cls
            seen = set()
            while c is not None and c not in seen:
                seen.add(c)
                kind = self.attr_kind.get(f"{c}.{name}")
                if kind is not None:
                    return f"{c}.{name}", kind
                bases = self.class_info.get(c, {}).get("bases", [])
                c = next((b for b in bases if b in self.class_info), None)
            if _LOCKISH.search(name):
                return f"{cls}.{name}", "lock"
            return None
        if tag == "n":
            parts = fsum["qual"].split(".<locals>.")
            while parts:
                q = ".<locals>.".join(parts)
                kind = s["types"].get(f"local:{q}:{name}")
                if kind is not None:
                    return f"{rel}::{q}.{name}", kind
                parts.pop()
            kind = s["types"].get(f"module:{name}")
            if kind is not None:
                return f"{rel}::{name}", kind
            if _LOCKISH.search(name):
                return f"{rel}::{name}", "lock"
            return None
        # tag == "a": attribute on a non-self receiver
        classes = [c for c in self.attr_classes.get(name, [])
                   if self.attr_kind.get(f"{c}.{name}") in
                   (_LOCK_KINDS | _WAITABLE_KINDS)]
        if len(classes) == 1:
            c = classes[0]
            return f"{c}.{name}", self.attr_kind[f"{c}.{name}"]
        if classes:
            return None  # ambiguous: resolving would conflate strangers
        if _LOCKISH.search(name):
            return f"?.{name}", "lock"
        return None

    def held_locks(self, refs: Sequence[str], rel: str,
                   fsum: dict) -> List[str]:
        """Resolved lock ids (lock/condition kinds only) for a held list."""
        out = []
        for ref in refs:
            r = self.resolve_lock(ref, rel, fsum)
            if r is not None and r[1] in _LOCK_KINDS and r[0] not in out:
                out.append(r[0])
        return out

    # ---- call resolution --------------------------------------------------

    def resolve_call(self, site: dict, rel: str,
                     fsum: dict) -> Optional[str]:
        """Function key for a call site, or None when unresolvable."""
        name, recv = site["name"], site["recv"]
        s = self.summaries[rel]
        if recv in ("self", "cls"):
            cls = fsum["cls"]
            seen: Set[str] = set()
            while cls is not None and cls not in seen:
                seen.add(cls)
                if name in self.class_info.get(cls, {}).get("methods", []):
                    return f"{self.class_file[cls]}::{cls}.{name}"
                bases = self.class_info.get(cls, {}).get("bases", [])
                cls = next((b for b in bases if b in self.class_info), None)
            return None
        if recv == "":
            # nested def in an enclosing scope
            parts = fsum["qual"].split(".<locals>.")
            while parts:
                q = ".<locals>.".join(parts)
                key = f"{rel}::{q}.<locals>.{name}"
                if key in self.functions:
                    return key
                parts.pop()
            if f"{rel}::{name}" in self.functions:
                return f"{rel}::{name}"
            fi = s["from_imports"].get(name)
            if fi is not None:
                target = self.module_file.get(fi[0])
                if target is not None:
                    key = f"{target}::{fi[1]}"
                    if key in self.functions:
                        return key
                return None
            cands = self.module_fns.get(name, [])
            if len(cands) == 1:
                return cands[0]
            return None
        if recv != "?":
            first = recv.split(".")[0]
            mod = s["imports"].get(first)
            if mod is not None:
                rest = recv.split(".")[1:]
                target = self.module_file.get(".".join([mod] + rest))
                if target is None and not rest:
                    target = self.module_file.get(mod)
                if target is not None:
                    key = f"{target}::{name}"
                    if key in self.functions:
                        return key
                return None
        if name in GENERIC_METHODS:
            return None
        cands2 = self.method_classes.get(name, [])
        if len(cands2) == 1:
            c = cands2[0]
            return f"{self.class_file[c]}::{c}.{name}"
        return None

    # ---- fixpoints: may_block and transitive lock acquisition -------------

    def _compute_flow(self) -> None:
        keys = sorted(self.functions)
        self.block_info: Dict[str, dict] = {}
        self.acq_locks: Dict[str, Dict[str, dict]] = {k: {} for k in keys}
        resolved_calls: Dict[str, List[Tuple[str, dict]]] = {}
        callers: Dict[str, List[str]] = {}
        for key in keys:
            fsum = self.functions[key]
            rel = self.file_of[key]
            sites = []
            for site in fsum["calls"]:
                g = self.resolve_call(site, rel, fsum)
                if g is not None and g != key:
                    sites.append((g, site))
                    callers.setdefault(g, []).append(key)
            resolved_calls[key] = sites
            if fsum["blocking"]:
                b = fsum["blocking"][0]
                self.block_info[key] = {
                    "kind": b["kind"], "line": b["line"],
                    "qual": fsum["qual"], "path": rel, "via": None}
            for acq in fsum["acquires"]:
                if acq["try"]:
                    continue
                r = self.resolve_lock(acq["ref"], rel, fsum)
                if r is not None and r[1] in _LOCK_KINDS:
                    self.acq_locks[key].setdefault(
                        r[0], {"line": acq["line"], "qual": fsum["qual"],
                               "path": rel, "via": None})
        self.resolved_calls = resolved_calls
        # may_block fixpoint (reverse propagation along call edges)
        work = sorted(self.block_info)
        while work:
            g = work.pop()
            for f in callers.get(g, []):
                if f in self.block_info:
                    continue
                line = next(s["line"] for (gg, s) in resolved_calls[f]
                            if gg == g)
                self.block_info[f] = {
                    "kind": self.block_info[g]["kind"], "line": line,
                    "qual": self.functions[f]["qual"],
                    "path": self.file_of[f], "via": g}
                work.append(f)
        # transitive lock acquisition fixpoint
        work = [k for k in keys if self.acq_locks[k]]
        while work:
            g = work.pop()
            for f in callers.get(g, []):
                changed = False
                for lock, w in self.acq_locks[g].items():
                    if lock in self.acq_locks[f]:
                        continue
                    line = next(s["line"] for (gg, s) in resolved_calls[f]
                                if gg == g)
                    self.acq_locks[f][lock] = {
                        "line": line, "qual": self.functions[f]["qual"],
                        "path": self.file_of[f], "via": g}
                    changed = True
                if changed:
                    work.append(f)

    def block_chain(self, key: str, limit: int = 8) -> str:
        """Human chain 'f -> g -> leaf (kind)' for a may-block function."""
        names = []
        k: Optional[str] = key
        seen: Set[str] = set()
        while k is not None and k not in seen and len(names) < limit:
            seen.add(k)
            info = self.block_info.get(k)
            if info is None:
                break
            names.append(self.functions[k]["qual"])
            k = info["via"]
        kind = self.block_info[key]["kind"]
        return " -> ".join(names) + f" [{kind}]"

    def block_leaf(self, key: str) -> dict:
        """The terminal (directly-blocking) function's info for a
        may-block function — kind and qual of the actual blocking site."""
        k = key
        seen: Set[str] = set()
        while k not in seen:
            seen.add(k)
            info = self.block_info[k]
            if info["via"] is None:
                return info
            k = info["via"]
        return self.block_info[key]

    def acq_chain(self, key: str, lock: str, limit: int = 8) -> str:
        names = []
        k: Optional[str] = key
        seen: Set[str] = set()
        while k is not None and k not in seen and len(names) < limit:
            seen.add(k)
            w = self.acq_locks.get(k, {}).get(lock)
            if w is None:
                break
            names.append(self.functions[k]["qual"])
            k = w["via"]
        return " -> ".join(names)

    # ---- the lock-order graph --------------------------------------------

    def lock_edges(self) -> Dict[Tuple[str, str], dict]:
        """(L, M) -> witness for every 'M acquired while L held' fact,
        direct or through calls. Self-edges are dropped: instances of one
        class share a lock id, so L->L is usually two objects."""
        edges: Dict[Tuple[str, str], dict] = {}

        def add(L: str, M: str, witness: dict) -> None:
            if L == M:
                return
            edges.setdefault((L, M), witness)

        for key in sorted(self.functions):
            fsum = self.functions[key]
            rel = self.file_of[key]
            for acq in fsum["acquires"]:
                if acq["try"]:
                    continue
                r = self.resolve_lock(acq["ref"], rel, fsum)
                if r is None or r[1] not in _LOCK_KINDS:
                    continue
                for L in self.held_locks(acq["held"], rel, fsum):
                    add(L, r[0], {"qual": fsum["qual"], "path": rel,
                                  "line": acq["line"], "chain": None})
            for g, site in self.resolved_calls[key]:
                held = self.held_locks(site["held"], rel, fsum)
                if not held:
                    continue
                for M in self.acq_locks.get(g, {}):
                    for L in held:
                        add(L, M, {"qual": fsum["qual"], "path": rel,
                                   "line": site["line"],
                                   "chain": self.acq_chain(g, M)})
        return edges


# ---------------------------------------------------------------------------
# model construction (cached per Project instance)
# ---------------------------------------------------------------------------

_MODELS: "weakref.WeakKeyDictionary[Project, Model]" = (
    weakref.WeakKeyDictionary())


def build_model(project: Project, cache: Optional[SummaryCache] = None,
                jobs: int = 0) -> Model:
    """Summarize every project file (cache-aware, optionally parallel) and
    join. `jobs` <= 1 means serial."""
    summaries: Dict[str, dict] = {}
    # read all sources up front (cheap, and keeps worker threads read-only
    # with respect to the Project's caches)
    sources = {rel: project.source(rel) for rel in project.files}

    def one(rel: str) -> Tuple[str, dict]:
        src = sources[rel]
        digest = source_digest(src)
        if cache is not None:
            hit = cache.get(rel, digest)
            if hit is not None:
                return rel, hit
        tree = project._trees.get(rel)
        if tree is None and rel not in project._trees:
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError:
                tree = None
        summary = summarize_file(rel, src, tree)
        if cache is not None:
            cache.put(rel, digest, summary)
        return rel, summary

    if jobs and jobs > 1 and len(project.files) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=jobs,
                thread_name_prefix="daftlint-summarize") as ex:
            for rel, summary in ex.map(one, project.files):
                summaries[rel] = summary
    else:
        for rel in project.files:
            summaries[rel] = one(rel)[1]
    if cache is not None:
        cache.save()
    return Model(project, summaries)


def model_for(project: Project) -> Model:
    """The shared Model for this Project, built on first use. The CLI can
    preconfigure caching/parallelism by setting `project.summary_cache`
    (a SummaryCache) and `project.summary_jobs` (int) before rules run."""
    model = _MODELS.get(project)
    if model is None:
        model = build_model(project,
                            cache=getattr(project, "summary_cache", None),
                            jobs=getattr(project, "summary_jobs", 0))
        _MODELS[project] = model
    return model
