"""DTL001 jit-purity: functions traced by jax.jit must stay pure.

Scope: files under daft_tpu/kernels/, daft_tpu/parallel/, and
daft_tpu/fuse/ — the fusion compiler emits jit-traced programs, and
fuse/segment.py (the plan-segment compiler) composes them into resident
pipelines whose donated buffers make any trace-time impurity fatal, not
just wrong. A traced
function is one decorated with `@jax.jit` / `@jit` /
`@functools.partial(jax.jit, ...)`, or passed (by name, lambda, or through
`jax.shard_map`/`jax.pmap`/`jax.vmap`) to a `jax.jit(...)` call.

Inside a traced function (nested defs included — they trace too) we flag:

- wall-clock / RNG calls (`time.*`, `random.*`, `np.random.*`): traced once
  at compile time, frozen forever after — silent nondeterminism;
- `print(...)`: fires at trace time only, lies about per-call behavior
  (jax.debug.print is the traced alternative);
- `global` statements: mutating module state from inside a trace runs once
  per compilation, not per call;
- host sync (`.item()`, `.tolist()`, `.block_until_ready()`,
  `jax.device_get`, `np.asarray(...)` on traced values): forces a device
  round-trip mid-trace or fails under jit outright.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..engine import Finding, Project, Rule, dotted_name

IMPURE_MODULES = {"time", "random"}
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
HOST_SYNC_FUNCS = {"jax.device_get", "np.asarray", "np.array",
                   "numpy.asarray", "numpy.array"}
TRACER_WRAPPERS = {"shard_map", "pmap", "vmap", "grad", "value_and_grad"}


def _is_jit_expr(node: ast.AST) -> bool:
    """`jit`, `jax.jit`, or `functools.partial(jax.jit, ...)`."""
    name = dotted_name(node)
    if name in ("jit", "jax.jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _traced_arg_names(call: ast.Call) -> List[str]:
    """Names of functions a jax.jit(...) call traces, unwrapping one level
    of shard_map/pmap/vmap, e.g. jax.jit(jax.shard_map(body, ...)) -> body."""
    out: List[str] = []
    for arg in call.args[:1]:
        if isinstance(arg, ast.Name):
            out.append(arg.id)
        elif isinstance(arg, ast.Call):
            inner = dotted_name(arg.func)
            if inner and inner.split(".")[-1] in TRACER_WRAPPERS and arg.args:
                first = arg.args[0]
                if isinstance(first, ast.Name):
                    out.append(first.id)
    return out


class JitPurityRule(Rule):
    code = "DTL001"
    name = "jit-purity"
    description = ("jit-traced kernels must not touch time/random/print/"
                   "global state or force host sync")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for rel in project.lint_files:
            segs = rel.split("/")[:-1]
            if ("kernels" not in segs and "parallel" not in segs
                    and "fuse" not in segs):
                continue
            tree = project.tree(rel)
            if tree is None:
                continue
            out.extend(self._check_module(rel, tree))
        return out

    def _check_module(self, rel: str, tree: ast.Module) -> List[Finding]:
        traced_names: Set[str] = set()
        traced_fns: List[ast.AST] = []
        lambdas_traced: List[ast.Lambda] = []
        all_defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                all_defs.setdefault(node.name, []).append(node)
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    traced_fns.append(node)
            elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
                traced_names.update(_traced_arg_names(node))
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Lambda):
                        lambdas_traced.append(arg)
                    elif isinstance(arg, ast.Call):
                        for a in arg.args[:1]:
                            if isinstance(a, ast.Lambda):
                                lambdas_traced.append(a)
        for name in traced_names:
            traced_fns.extend(all_defs.get(name, []))
        out: List[Finding] = []
        seen = set()
        for fn in traced_fns:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            out.extend(self._check_traced(rel, fn, fn.name))
        for lam in lambdas_traced:
            out.extend(self._check_traced(rel, lam, "<lambda>"))
        return out

    def _check_traced(self, rel: str, fn: ast.AST,
                      label: str) -> List[Finding]:
        out: List[Finding] = []

        def flag(node: ast.AST, msg: str) -> None:
            out.append(self.finding(
                rel, getattr(node, "lineno", 1),
                f"{msg} inside jit-traced `{label}`"))

        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                flag(node, "`global` statement (trace-time module mutation)")
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname == "print":
                    flag(node, "`print` call (fires at trace time only)")
                elif fname is not None:
                    root = fname.split(".")[0]
                    if "." in fname and root in IMPURE_MODULES:
                        flag(node, f"impure call `{fname}`")
                    elif fname.startswith(("np.random.", "numpy.random.",
                                           "jax.random.PRNGKey")):
                        flag(node, f"impure call `{fname}`")
                    elif fname in HOST_SYNC_FUNCS:
                        flag(node, f"host sync `{fname}`")
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in HOST_SYNC_METHODS):
                    flag(node, f"host sync `.{node.func.attr}()`")
        return out
