"""Rule registry. Adding an invariant = one module here + one entry below."""

from .jit_purity import JitPurityRule
from .lock_discipline import LockDisciplineRule
from .collective_safety import CollectiveSafetyRule
from .fault_sites import FaultSiteCoverageRule
from .error_hygiene import ErrorHygieneRule
from .span_coverage import SpanCoverageRule
from .log_hygiene import LogHygieneRule
from .ambient_state import AmbientStateRule
from .lock_order import LockOrderRule
from .blocking_under_lock import BlockingUnderLockRule
from .ledger_balance import LedgerBalanceRule
from .thread_discipline import ThreadDisciplineRule

ALL_RULES = [
    JitPurityRule(),
    LockDisciplineRule(),
    CollectiveSafetyRule(),
    FaultSiteCoverageRule(),
    ErrorHygieneRule(),
    SpanCoverageRule(),
    LogHygieneRule(),
    AmbientStateRule(),
    LockOrderRule(),
    BlockingUnderLockRule(),
    LedgerBalanceRule(),
    ThreadDisciplineRule(),
]

RULES_BY_CODE = {r.code: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_CODE", "JitPurityRule",
           "LockDisciplineRule", "CollectiveSafetyRule",
           "FaultSiteCoverageRule", "ErrorHygieneRule", "SpanCoverageRule",
           "LogHygieneRule", "AmbientStateRule", "LockOrderRule",
           "BlockingUnderLockRule", "LedgerBalanceRule",
           "ThreadDisciplineRule"]
