"""DTL010 blocking-under-lock: no blocking operation is reachable while
an engine state lock is held.

A thread that blocks while holding a lock stalls every other thread
needing that lock — the PR 16 class of bug (a wedged rx thread holding
supervisor state, a handshake recv with no deadline serializing all
spawns). The shared interprocedural model supplies, per function, the
blocking operations it performs directly and the calls it makes, each
with the locks lexically held; a fixpoint marks functions that can reach
a blocking operation through any call chain.

Blocking operations: socket accept/recv/send/connect, file IO,
``Future.result``, ``queue.get``, ``subprocess``, ``time.sleep``,
thread/process joins, and semaphore/barrier/event waits.

Two whitelists, both part of the rule's contract:

- **Condition waits.** ``cond.wait()``/``wait_for()`` RELEASES the
  condition's lock for the duration, so waiting on a held condition is
  not blocking *under that condition* (it still counts against any other
  lock held at the same time — and a function containing a cond-wait is
  still blocking from its CALLERS' perspective, since their locks are
  not released).
- **IO-serialization locks.** A lock whose declaration carries
  ``# daftlint: io-lock`` exists to serialize one IO stream (a
  per-socket ``send_lock``, a collective round lock) and is held across
  that IO *by contract*. Such locks are exempt here but still ordered by
  DTL009 — acquiring a state lock while holding an io-lock remains a
  finding there.
"""

from __future__ import annotations

from typing import List

from ..engine import Finding, Project, Rule
from ..interproc import _WAITABLE_KINDS, model_for


class BlockingUnderLockRule(Rule):
    code = "DTL010"
    name = "blocking-under-lock"
    description = ("no blocking call (socket/file IO, future/queue/"
                   "subprocess waits, sleeps) may be reachable while "
                   "holding an engine lock")

    def run(self, project: Project) -> List[Finding]:
        model = model_for(project)
        out: List[Finding] = []
        for key in sorted(model.functions):
            fsum = model.functions[key]
            rel = model.file_of[key]

            def held_minus_io(raw_refs):
                return [h for h in model.held_locks(raw_refs, rel, fsum)
                        if h not in model.io_locks]

            for b in fsum["blocking"]:
                held = held_minus_io(b["held"])
                if b.get("rel"):
                    r = model.resolve_lock(b["rel"], rel, fsum)
                    if r is not None:
                        # the cond-wait whitelist: the wait releases the
                        # very lock it waits on
                        held = [h for h in held if h != r[0]]
                for lock in held:
                    out.append(self.finding(
                        rel, b["line"],
                        f"blocking `{b['kind']}` in `{fsum['qual']}` "
                        f"while holding `{lock}`"))
            for acq in fsum["acquires"]:
                # with/acquire on a semaphore, barrier or event is itself
                # a wait (they are not locks, so they are not in held sets)
                r = model.resolve_lock(acq["ref"], rel, fsum)
                if r is None or r[1] not in _WAITABLE_KINDS or acq["try"]:
                    continue
                for lock in held_minus_io(acq["held"]):
                    out.append(self.finding(
                        rel, acq["line"],
                        f"blocking `{r[1]} acquire ({r[0]})` in "
                        f"`{fsum['qual']}` while holding `{lock}`"))
            for gkey, site in model.resolved_calls[key]:
                info = model.block_info.get(gkey)
                if info is None:
                    continue
                held = held_minus_io(site["held"])
                if not held:
                    continue
                g = model.functions[gkey]
                leaf = model.block_leaf(gkey)
                for lock in held:
                    out.append(self.finding(
                        rel, site["line"],
                        f"call to `{g['qual']}` from `{fsum['qual']}` may "
                        f"block ({leaf['kind']} in `{leaf['qual']}`) "
                        f"while holding `{lock}`"))
        return out
