"""DTL007 log hygiene: engine modules log through the structured logger.

daft_tpu/obs/log.py is the engine's one logging backend: JSON-lines
records with cross-thread query-id context, a bounded ring the diagnostics
bundles snapshot, and stdlib forwarding. Ad-hoc output anywhere else —
bare ``print``, ``warnings.warn``, direct stdlib ``logging`` calls, or a
module logger bound via ``logging.getLogger`` — produces lines the flight
recorder cannot attribute or bundle, which is exactly the blind spot this
PR closes.

Flagged, per engine file (obs/log.py itself is the sanctioned backend and
exempt):

- ``print(...)`` calls
- ``warnings.warn(...)`` / ``warnings.warn_explicit(...)``
- any ``logging.*(...)`` call (``logging.getLogger``, ``logging.warning``,
  ...) and ``from logging import ...``
- calls on a name assigned from ``logging.getLogger(...)`` in the same
  file (the classic module-logger pattern)

Deliberate survivors — terminal-UI surfaces like progress bars and the
explain/show REPL output — are grandfathered in baseline.json with
comments (the DTL004/005/006 discipline: the backlog stays visible, new
ad-hoc logging fails the run).
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..engine import Finding, Project, Rule, dotted_name

_EXEMPT = ("daft_tpu/obs/log.py",)

MSG_PRINT = ("bare `print()` call bypasses the structured engine logger "
             "(daft_tpu/obs/log.py) — use obs.log.get_logger(...), or "
             "baseline a deliberate terminal-UI surface")
MSG_WARNINGS = ("`warnings.warn` bypasses the structured engine logger — "
                "use obs.log.get_logger(...).warning(...)")
MSG_LOGGING = ("stdlib `logging` usage bypasses the structured engine "
               "logger — use obs.log.get_logger(...)")


def _stdlib_logger_names(tree: ast.Module) -> Set[str]:
    """Names assigned from ``logging.getLogger(...)`` anywhere in the file
    (calls on them are ad-hoc logging even though `logging.` never appears
    at the call site)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        if dotted_name(node.value.func) != "logging.getLogger":
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


class LogHygieneRule(Rule):
    code = "DTL007"
    name = "log-hygiene"
    description = ("engine modules log through the structured engine "
                   "logger (daft_tpu/obs/log.py) — no bare print(), "
                   "warnings.warn, or stdlib logging calls")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for rel in project.lint_files:
            if rel in _EXEMPT:
                continue
            tree = project.tree(rel)
            if tree is None:
                continue
            loggers = _stdlib_logger_names(tree)
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom) and \
                        node.module == "logging":
                    out.append(self.finding(rel, node.lineno, MSG_LOGGING))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                if name == "print":
                    out.append(self.finding(rel, node.lineno, MSG_PRINT))
                elif name in ("warnings.warn", "warnings.warn_explicit"):
                    out.append(self.finding(rel, node.lineno, MSG_WARNINGS))
                elif name == "logging" or name.startswith("logging."):
                    out.append(self.finding(rel, node.lineno, MSG_LOGGING))
                elif "." in name and name.split(".", 1)[0] in loggers:
                    out.append(self.finding(rel, node.lineno, MSG_LOGGING))
        return out
