"""DTL009 lock-order: the global lock-acquisition-order graph must be
acyclic — a cycle is a potential deadlock.

Built on the shared interprocedural model (tools/daftlint/interproc.py):
an edge ``L -> M`` exists when some function acquires M while holding L,
either lexically (nested ``with`` blocks, or the ``acquire()/try/
finally: release()`` idiom) or through a call chain (holding L and
calling a function that eventually acquires M). Lock identity is
``ClassName.attr`` for instance locks — all instances of a class share
one node, the standard conflation for order analysis — and
``path::NAME`` for module globals and closure-local locks.

Each strongly connected component of two or more locks is reported ONCE,
with the full ring and a witness function per edge (both chains of a
two-lock inversion, per the contract). Self-edges are not reported:
``L -> L`` under instance conflation is usually a parent/child pair of
the same class (e.g. forwarding MemoryLedgers), not re-entry — DTL002
and the runtime cover genuine re-entry.

Try-acquires (``acquire(blocking=False)``) never create edges: a
trylock cannot deadlock. IO-serialization locks (``# daftlint:
io-lock``) still participate — exempting them from DTL010's
blocking-under-lock check does not exempt them from ordering.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..engine import Finding, Project, Rule
from ..interproc import model_for


def _sccs(nodes: List[str],
          adj: Dict[str, List[str]]) -> List[List[str]]:
    """Tarjan's SCC, iterative, deterministic (sorted inputs)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            succs = adj.get(node, [])
            for i in range(pi, len(succs)):
                nxt = succs[i]
                if nxt not in index:
                    work[-1] = (node, i + 1)
                    work.append((nxt, 0))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


def _find_ring(comp: List[str], adj: Dict[str, List[str]],
               members: Set[str]) -> List[str]:
    """A deterministic simple cycle through the SCC, starting from its
    smallest lock: [A, B, ..., A]."""
    start = comp[0]
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = None
        for cand in adj.get(node, []):
            if cand == start and len(path) > 1:
                return path + [start]
            if cand in members and cand not in seen:
                nxt = cand
                break
        if nxt is None:
            # dead end inside the SCC: backtrack (guaranteed to terminate
            # because the SCC is strongly connected)
            path.pop()
            if not path:
                return [start, start]
            node = path[-1]
            continue
        path.append(nxt)
        seen.add(nxt)
        node = nxt


class LockOrderRule(Rule):
    code = "DTL009"
    name = "lock-order"
    description = ("the global lock-acquisition-order graph (across call "
                   "chains) must be acyclic; a cycle is a potential "
                   "deadlock")

    def run(self, project: Project) -> List[Finding]:
        model = model_for(project)
        edges = model.lock_edges()
        adj: Dict[str, List[str]] = {}
        for (L, M) in sorted(edges):
            adj.setdefault(L, []).append(M)
        nodes = sorted(set(adj) | {M for (_L, M) in edges})
        out: List[Finding] = []
        for comp in _sccs(nodes, adj):
            if len(comp) < 2:
                continue
            members = set(comp)
            ring = _find_ring(comp, adj, members)
            legs = []
            for a, b in zip(ring, ring[1:]):
                w = edges[(a, b)]
                leg = f"`{b}` (in `{w['qual']}`"
                if w.get("chain"):
                    leg += f" via {w['chain']}"
                leg += ")"
                legs.append(leg)
            first = edges[(ring[0], ring[1])]
            out.append(self.finding(
                first["path"], first["line"],
                f"potential deadlock: lock-order cycle `{ring[0]}` -> "
                + " -> ".join(legs)))
        return out
