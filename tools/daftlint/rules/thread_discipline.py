"""DTL012 thread-discipline: every engine thread is nameable and
accountable.

``serve.leaked_thread_count()`` / ``dt.shutdown`` find engine threads by
scanning ``threading.enumerate()`` for the ``_ENGINE_THREAD_PREFIXES``
inventory — a nameless (``Thread-3``) or unprefixed thread is invisible
to leak accounting, and a non-daemon engine thread can pin interpreter
exit. The rule enforces, for every ``threading.Thread(...)`` in the
project:

- an explicit ``name=`` keyword whose STATIC prefix (string literal, or
  the literal head of an f-string like ``f"daft-dist-rx-{wid}"``) starts
  with ``daft-``;
- an explicit ``daemon=`` keyword (a literal ``True``/``False`` — the
  choice must be visible at the spawn site, not inherited);
- when the project declares a ``_ENGINE_THREAD_PREFIXES`` inventory, the
  static name prefix must be covered by some inventory entry — a new
  subsystem prefix that forgets to register itself is caught statically,
  before the zero-leak tests can miss it at runtime.

``ThreadPoolExecutor(...)`` gets the same treatment via
``thread_name_prefix=`` (executor threads are pool-managed, so no daemon
requirement).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..engine import Finding, Project, Rule, dotted_name
from ..interproc import _static_str_prefix


def _inventory(project: Project) -> Tuple[Optional[str],
                                          Tuple[str, ...]]:
    """(declaring file, prefixes) for the project's
    ``_ENGINE_THREAD_PREFIXES`` tuple, or (None, ()) when absent."""
    for rel in project.files:
        if "_ENGINE_THREAD_PREFIXES" not in project.source(rel):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name)
                       and t.id == "_ENGINE_THREAD_PREFIXES"
                       for t in node.targets):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                vals = tuple(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
                return rel, vals
    return None, ()


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class ThreadDisciplineRule(Rule):
    code = "DTL012"
    name = "thread-discipline"
    description = ("threading.Thread needs an explicit daft- prefixed "
                   "name= and a literal daemon= flag (and executors a "
                   "daft- thread_name_prefix), covered by the "
                   "_ENGINE_THREAD_PREFIXES leak-accounting inventory")

    def run(self, project: Project) -> List[Finding]:
        inv_file, prefixes = _inventory(project)
        out: List[Finding] = []
        for rel in project.lint_files:
            tree = project.tree(rel)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = dotted_name(node.func)
                if dotted is None:
                    continue
                last = dotted.split(".")[-1]
                if last == "Thread" and dotted in ("threading.Thread",
                                                   "Thread"):
                    self._check_thread(node, rel, inv_file, prefixes, out)
                elif last in ("ThreadPoolExecutor",):
                    self._check_executor(node, rel, inv_file, prefixes,
                                         out)
        return out

    def _check_thread(self, node: ast.Call, rel: str,
                      inv_file: Optional[str],
                      prefixes: Tuple[str, ...],
                      out: List[Finding]) -> None:
        name_kw = _kw(node, "name")
        if name_kw is None:
            out.append(self.finding(
                rel, node.lineno,
                "threading.Thread without an explicit name= — leak "
                "accounting cannot see a nameless thread"))
        else:
            self._check_prefix(node, rel, "name", name_kw, inv_file,
                               prefixes, out)
        daemon_kw = _kw(node, "daemon")
        if daemon_kw is None:
            out.append(self.finding(
                rel, node.lineno,
                "threading.Thread without an explicit daemon= flag — "
                "a non-daemon engine thread can pin interpreter exit; "
                "make the choice visible at the spawn site"))
        elif not (isinstance(daemon_kw, ast.Constant)
                  and isinstance(daemon_kw.value, bool)):
            out.append(self.finding(
                rel, node.lineno,
                "threading.Thread daemon= must be a literal "
                "True/False, not a computed value"))

    def _check_executor(self, node: ast.Call, rel: str,
                        inv_file: Optional[str],
                        prefixes: Tuple[str, ...],
                        out: List[Finding]) -> None:
        pref_kw = _kw(node, "thread_name_prefix")
        if pref_kw is None:
            out.append(self.finding(
                rel, node.lineno,
                "ThreadPoolExecutor without thread_name_prefix= — its "
                "workers are invisible to leak accounting"))
        else:
            self._check_prefix(node, rel, "thread_name_prefix", pref_kw,
                               inv_file, prefixes, out)

    def _check_prefix(self, node: ast.Call, rel: str, kw_name: str,
                      value: ast.expr, inv_file: Optional[str],
                      prefixes: Tuple[str, ...],
                      out: List[Finding]) -> None:
        static = _static_str_prefix(value)
        if static is None:
            out.append(self.finding(
                rel, node.lineno,
                f"thread {kw_name}= must be a string literal or an "
                f"f-string with a literal head, so the daft- prefix is "
                f"statically checkable"))
            return
        if not static.startswith("daft-"):
            out.append(self.finding(
                rel, node.lineno,
                f"thread {kw_name}= `{static}...` does not start with "
                f"`daft-` — engine threads must be identifiable"))
            return
        # the summarizer's own pool and similar tooling threads are
        # daft-prefixed but live outside the serve inventory
        if inv_file is None or rel == inv_file:
            return
        if not any(static.startswith(p) for p in prefixes):
            out.append(self.finding(
                rel, node.lineno,
                f"thread prefix `{static}` is not covered by "
                f"_ENGINE_THREAD_PREFIXES in {inv_file} — "
                f"leaked_thread_count() would be blind to it"))
