"""DTL008 no-ambient-state: module-level mutable engine state is pinned.

The serving runtime de-globalized per-query execution state into
QueryContext (daft_tpu/serve/qcontext.py): the process-global context
holds only config + runner, and everything mutable a query touches —
stats, breakers, deadline, ledger share — is per-query. This rule pins
that refactor statically so ambient globals don't creep back:

Flagged, per engine file:

- a module-level name bound to a container (literal, comprehension, or
  ``dict/list/set/deque/...`` constructor) that the file MUTATES —
  subscript stores, mutating method calls (``.append/.update/.pop/...``),
  augmented assigns. A constant lookup table that is only ever read is
  not state and never flagged;
- a module-level name bound to a class-like constructor call (CamelCase
  callee): an engine OBJECT at module scope is ambient state — its
  internals mutate even when the binding never does;
- a ``global X`` declaration inside a function (module-global rebinding
  from code paths — the classic creeping-counter pattern).

Exempt (not state, or not shared):

- synchronization primitives (``threading.Lock/RLock/Condition/Event/
  Semaphore/Barrier/local``) — coordination, not data;
- immutable-value factories (``re.compile``, ``frozenset``, ``tuple``,
  ``object()`` sentinels, ``TypeVar``, lowercase/scalar constructors like
  ``np.uint64``), ``__all__``, and ``get_logger(...)`` channels (the log
  ring itself is accounted state in obs/log.py);
- names in the REGISTRY whitelist below: the sanctioned process-wide
  registries (the "context/registry whitelist" — each is deliberately
  global, documented, and surfaced by dt.health()).

Deliberate survivors outside the whitelist are grandfathered in
baseline.json with comments (the DTL004/005/006/007 discipline: the
backlog stays visible, NEW ambient state fails the run).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..engine import Finding, Project, Rule, dotted_name

# (path, name): the sanctioned process-wide registries. Everything here is
# EITHER pure config/bookkeeping the health snapshot exposes, or the root
# account per-query state forwards into. Adding an entry is an
# architecture decision — prefer QueryContext.
REGISTRY_WHITELIST: Set[Tuple[str, str]] = {
    # root memory account: per-query child ledgers forward their deltas
    # here so dt.health() sees process totals
    ("daft_tpu/spill.py", "MEMORY_LEDGER"),
    # flight recorder ring + process metrics registry (observability
    # surfaces; bounded)
    ("daft_tpu/obs/querylog.py", "QUERY_LOG"),
    ("daft_tpu/profile/metrics.py", "METRICS"),
    # health snapshot's weak registries (latest breakers / admission)
    ("daft_tpu/obs/health.py", "_breakers"),
    ("daft_tpu/obs/health.py", "_admission"),
    # live streaming channels (weak): the dt.health() channel-occupancy
    # view; entries die with their pipeline
    ("daft_tpu/stream/channel.py", "_channels"),
    # result cache: process-wide by design (reference PartitionSetCache)
    ("daft_tpu/runners.py", "_PARTITION_SET_CACHE"),
    # live serving runtimes, for engine-wide drain at dt.shutdown()
    ("daft_tpu/serve/runtime.py", "_RUNTIMES"),
    # actor pools persist across queries by design (model weights)
    ("daft_tpu/actor_pool.py", "_pools"),
    # the process's distributed worker pool (one supervised fleet per
    # process, torn down by dt.shutdown/atexit)
    ("daft_tpu/dist/supervisor.py", "_POOL"),
    # health snapshot's weak ref to the latest worker pool
    ("daft_tpu/obs/health.py", "_cluster"),
    # immutable struct.Struct frame-header codec, not state
    # immutable frame-header struct (protocol v2: len + flags + crc)
    ("daft_tpu/dist/transport.py", "_HDR"),
    # one peer-allgather plane per process (cluster membership is
    # process-lifetime state, like the jax distributed runtime it mirrors)
    ("daft_tpu/dist/peer.py", "_GROUP"),
    # cluster identity recorded at init_distributed (coordinator/nproc/pid)
    ("daft_tpu/parallel/multihost.py", "_CLUSTER"),
    # query-velocity subsystem (daft_tpu/adapt/, README "Plan & program
    # cache"): process-level by design — the whole point is reuse across
    # queries. All bounded (LRU byte caps / history depth caps), all
    # ledger-accounted, all clearable.
    ("daft_tpu/adapt/plancache.py", "PLAN_CACHE"),
    ("daft_tpu/adapt/history.py", "HISTORY"),
    ("daft_tpu/adapt/resultcache.py", "RESULT_CACHE"),
    # persistent cache store (daft_tpu/persist/): durable mirrors of the
    # adapt/ caches plus the on-disk result tier — process-level by
    # design (warm-start across restarts), bounded (keep-last-K artifact
    # pruning / persist_result_bytes LRU), fail-open everywhere
    ("daft_tpu/persist/artifacts.py", "ARTIFACTS"),
    ("daft_tpu/persist/resultstore.py", "RESULT_STORE"),
    # FDO planning collector: a thread-local scope marker, not shared state
    ("daft_tpu/adapt/fdo.py", "_tl"),
    # live query-progress registry (obs/cluster.py): one entry per
    # RUNNING execution, registered/unregistered by execute_plan — the
    # dt.health()["queries"] source; bounded by concurrent query count
    ("daft_tpu/obs/cluster.py", "_progress"),
    # the process's peer-shuffle piece store (dist/peerplane.py): one per
    # worker process, pieces dropped per shuffle id at query finish and
    # cleared whole on worker exit — bounded by live shuffles
    ("daft_tpu/dist/peerplane.py", "_PLANE"),
    # dynamic-batching subsystem (daft_tpu/batch/): pinned model pools
    # persist across queries BY DESIGN (weights load once per process,
    # LRU-bounded by cfg.model_cache_bytes, ledger-accounted, torn down
    # by dt.shutdown); the jit cache keys compiled applies per model fn;
    # the flush counters feed dt.health()["batching"] (bounded dict)
    ("daft_tpu/batch/actors.py", "_model_pools"),
    ("daft_tpu/batch/device.py", "_jit_cache"),
    ("daft_tpu/batch/executor.py", "_proc_counts"),
    # device-residency process counters (daft_tpu/fuse/segment.py):
    # fixed-key, lock-guarded dict mirrored into dt.health()["device"] —
    # engine-wide residency totals outlive any one query by design,
    # reset only via reset_process_counters()
    ("daft_tpu/fuse/segment.py", "_PROC_COUNTERS"),
}

_CONTAINER_CTOR_BASES = {
    "dict", "list", "set", "bytearray", "deque", "defaultdict",
    "OrderedDict", "Counter", "Queue", "LifoQueue", "PriorityQueue",
    "SimpleQueue", "WeakSet", "WeakValueDictionary", "WeakKeyDictionary",
}

_EXEMPT_CALL_BASES = {
    # synchronization, not data
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier",
    # immutable results / factories
    "TypeVar",
    "get_logger",  # a channel into the (accounted) obs/log ring
    "getLogger",
}

_MUTATING_METHODS = {
    "append", "appendleft", "add", "update", "pop", "popleft", "clear",
    "setdefault", "extend", "remove", "discard", "insert", "put",
}

MSG_BINDING = ("module-level mutable binding `{name}` is ambient engine "
               "state — move it onto QueryContext / into the registry "
               "whitelist (tools/daftlint/rules/ambient_state.py), or "
               "baseline a deliberate survivor with a comment")
MSG_OBJECT = ("module-level engine object `{name}` is ambient state — "
              "move it onto QueryContext / into the registry whitelist "
              "(tools/daftlint/rules/ambient_state.py), or baseline a "
              "deliberate survivor with a comment")
MSG_GLOBAL = ("function `{fn}` rebinds module global `{name}` — ambient "
              "state mutation; route it through a context/registry "
              "object, or baseline a deliberate survivor with a comment")


def _call_base(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name is None:
        return None
    return name.rsplit(".", 1)[-1]


def _is_classlike(base: str) -> bool:
    """CamelCase callee: SomeClass(...) — an object whose internals mutate
    even when the binding never does. Lowercase/scalar constructors
    (np.uint64, pa.schema, object, namedtuple, re.compile) are value
    factories and stay exempt."""
    return base[:1].isupper() and not base.isupper()


def _mutated_names(tree: ast.Module) -> Set[str]:
    """Names whose bound container is mutated anywhere in the file:
    subscript stores/deletes, mutating method calls, augmented assigns."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name):
                    out.add(tgt.value.id)
                elif isinstance(node, ast.AugAssign) and \
                        isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name):
                    out.add(tgt.value.id)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.attr in _MUTATING_METHODS:
            out.add(node.func.value.id)
    return out


class AmbientStateRule(Rule):
    code = "DTL008"
    name = "no-ambient-state"
    description = ("module-level mutable engine state must live in the "
                   "context/registry whitelist — per-query state belongs "
                   "on QueryContext (daft_tpu/serve/qcontext.py)")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for rel in project.lint_files:
            tree = project.tree(rel)
            if tree is None:
                continue
            mutated = _mutated_names(tree)
            for node in tree.body:
                targets: List[ast.expr] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if value is None:
                    continue
                is_container = isinstance(
                    value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                            ast.SetComp, ast.DictComp))
                is_object = False
                if isinstance(value, ast.Call):
                    base = _call_base(value)
                    if base is None or base in _EXEMPT_CALL_BASES:
                        continue
                    if base in _CONTAINER_CTOR_BASES:
                        is_container = True
                    elif _is_classlike(base):
                        is_object = True
                if not (is_container or is_object):
                    continue
                for tgt in targets:
                    if not isinstance(tgt, ast.Name) or tgt.id == "__all__":
                        continue
                    if (rel, tgt.id) in REGISTRY_WHITELIST:
                        continue
                    if is_container and tgt.id not in mutated:
                        continue  # read-only lookup table, not state
                    msg = MSG_OBJECT if is_object else MSG_BINDING
                    out.append(self.finding(
                        rel, node.lineno, msg.format(name=tgt.id)))
            # `global X` declarations inside functions
            for fn in ast.walk(tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                for stmt in ast.walk(fn):
                    if not isinstance(stmt, ast.Global):
                        continue
                    for name in stmt.names:
                        if (rel, name) in REGISTRY_WHITELIST:
                            continue
                        out.append(self.finding(
                            rel, stmt.lineno,
                            MSG_GLOBAL.format(fn=fn.name, name=name)))
        return out
