"""DTL005 error-hygiene: migrated modules stay on the DaftError hierarchy.

Port of the former tools/check_error_hygiene.py into the rule framework,
keeping its incremental-adoption contract: modules listed in MIGRATED (the
list only grows, never shrinks) must not

1. raise raw builtin exceptions (``raise ValueError(...)`` and friends) —
   migrated modules raise the typed hierarchy so ``except DaftError`` stays
   the engine-wide catch-all (NotImplementedError stays exempt:
   abstract-method stubs are idiomatic);
2. contain bare ``except Exception:`` / ``except BaseException:`` /
   ``except:`` handlers whose body is ONLY ``pass`` — swallowed failures
   hide the exact signals the retry layers and circuit breakers key on.

Beyond MIGRATED, any file whose source carries a ``# daftlint: migrated``
marker opts itself into the same contract — new modules declare migration
in-file instead of editing this list.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ..engine import Finding, Project, Rule

# Modules migrated to the DaftError hierarchy. Entries are appended as
# modules migrate and NEVER removed (tests/test_error_hygiene.py pins the
# floor) — regressing a migrated module is exactly what this rule catches.
MIGRATED = [
    "daft_tpu/errors.py",
    "daft_tpu/faults.py",
    "daft_tpu/context.py",
    "daft_tpu/expressions.py",
    "daft_tpu/table.py",
    "daft_tpu/io/scan.py",
    "daft_tpu/actor_pool.py",
    "daft_tpu/scheduler.py",
    "daft_tpu/spill.py",
    "daft_tpu/io/object_store.py",
]

MIGRATED_MARKER = "# daftlint: migrated"

# builtin exception constructors a migrated module must not raise raw
RAW_RAISES = {
    "ValueError", "TypeError", "RuntimeError", "Exception", "BaseException",
    "IOError", "OSError", "FileNotFoundError", "PermissionError",
    "KeyError", "IndexError", "ArithmeticError", "ZeroDivisionError",
}

Violation = Tuple[int, str]


def check_tree(tree: ast.AST) -> List[Violation]:
    """(lineno, message) violations in a parsed module."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in RAW_RAISES:
                out.append((node.lineno,
                            f"raw `raise {name}` — use the DaftError "
                            "hierarchy (daft_tpu/errors.py)"))
        elif isinstance(node, ast.Try):
            for h in node.handlers:
                if not (len(h.body) == 1 and isinstance(h.body[0], ast.Pass)):
                    continue
                label = None
                if h.type is None:  # `except:` — swallows BaseException
                    label = "except:"
                elif (isinstance(h.type, ast.Name)
                        and h.type.id in ("Exception", "BaseException")):
                    label = f"except {h.type.id}:"
                elif isinstance(h.type, ast.Tuple) and any(
                        isinstance(e, ast.Name)
                        and e.id in ("Exception", "BaseException")
                        for e in h.type.elts):
                    label = "except (... Exception ...):"
                if label is not None:
                    out.append((h.lineno,
                                f"bare `{label} pass` swallows failures the "
                                "retry/breaker layers need to see — handle, "
                                "re-raise typed, or narrow"))
    return out


def check_source(source: str, relpath: str = "<string>") -> List[Violation]:
    """Convenience used by tests: parse then check."""
    return check_tree(ast.parse(source, filename=relpath))


class ErrorHygieneRule(Rule):
    code = "DTL005"
    name = "error-hygiene"
    description = ("migrated modules must not raise raw builtins or swallow "
                   "`except Exception: pass`")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        migrated = set(MIGRATED)
        for rel in project.lint_files:
            if rel not in migrated and MIGRATED_MARKER not in project.source(rel):
                continue
            tree = project.tree(rel)
            if tree is None:
                continue
            out.extend(self.finding(rel, lineno, msg)
                       for lineno, msg in check_tree(tree))
        return out
