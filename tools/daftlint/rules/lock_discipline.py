"""DTL002 lock-discipline: state written under a lock is ALWAYS written
under that lock — a lightweight static race detector.

Scope: every file under lint (the invariant matters most in execution.py,
actor_pool.py, spill.py, faults.py, and io/object_store.py, but holds
engine-wide).

Model, per class: any attribute assigned (`self.x = ...`, `self.x += ...`,
`self.x[k] = ...`) inside a `with self.<lockish>:` block — where <lockish>
is an attribute whose name contains lock/cond/mutex — is "guarded". Every
other write to a guarded attribute outside such a block is a finding,
except in `__init__`/`__post_init__`/`__new__` (construction happens before
the object is shared). The same model applies at module scope: module
globals assigned under `with <lockish-name>:` inside any function must
never be assigned outside one (module top level, which runs at import
before threads exist, is exempt).

Deliberately lightweight: reads are not checked, `.append()`-style mutating
method calls are not tracked (too many false positives on single-consumer
structures), lock scope is lexical (a closure DEFINED under a lock is
treated as running under it). When a write is intentionally lock-free
(single-threaded phase, monotonic flag), suppress with
`# daftlint: disable=DTL002` and say why, or baseline it.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set, Tuple

from ..engine import Finding, Project, Rule

_LOCKISH = re.compile(r"lock|cond|mutex", re.IGNORECASE)
_INIT_METHODS = {"__init__", "__post_init__", "__new__"}

# (attr_name, lineno, under_lock)
_Write = Tuple[str, int, bool]


def _self_attr_written(target: ast.AST) -> Optional[str]:
    """Attribute name when `target` writes self.<attr> or self.<attr>[...]."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"):
        return target.attr
    return None


def _module_name_written(target: ast.AST,
                         module_names: Set[str],
                         declared_global: Set[str]) -> Optional[str]:
    """Module-global name when `target` writes one: a plain Name declared
    `global` in the enclosing function, or a subscript store into a name
    bound at module top level (`_plans[site] = ...`)."""
    if isinstance(target, ast.Name) and target.id in declared_global:
        return target.id
    if isinstance(target, ast.Subscript) and isinstance(target.value, ast.Name):
        name = target.value.id
        if name in module_names:
            return name
    return None


def _is_self_lock(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and _LOCKISH.search(expr.attr) is not None)


def _is_module_lock(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Name) and _LOCKISH.search(expr.id) is not None


def _assign_targets(node: ast.stmt) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target] if getattr(node, "value", True) is not None else []
    return []


class LockDisciplineRule(Rule):
    code = "DTL002"
    name = "lock-discipline"
    description = ("attributes/globals written under a lock must never be "
                   "written outside it")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for rel in project.lint_files:
            tree = project.tree(rel)
            if tree is None:
                continue
            out.extend(self._check_module_scope(rel, tree))
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(rel, node))
        return out

    # --- class scope ------------------------------------------------------

    def _check_class(self, rel: str, cls: ast.ClassDef) -> List[Finding]:
        writes: List[_Write] = []      # outside init
        guarded: Set[str] = set()
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._collect_fn(item, False, writes, guarded,
                             item.name in _INIT_METHODS)
        if not guarded:
            return []
        return [
            self.finding(rel, lineno,
                         f"`self.{attr}` is written under `{cls.name}`'s "
                         "lock elsewhere but written here without it")
            for attr, lineno, under in writes
            if attr in guarded and not under
        ]

    def _collect_fn(self, fn: ast.AST, under: bool, writes: List[_Write],
                    guarded: Set[str], in_init: bool) -> None:
        """Record self-attribute writes in `fn`'s body with their lexical
        lock state; writes under a self-lock mark the attribute guarded."""

        def visit(node: ast.AST, under: bool) -> None:
            if isinstance(node, ast.With):
                locked = under or any(
                    _is_self_lock(item.context_expr)
                    for item in node.items)
                for child in node.body:
                    visit(child, locked)
                return
            for tgt in _assign_targets(node) if isinstance(node, ast.stmt) else []:
                attr = _self_attr_written(tgt)
                if attr is not None:
                    if under:
                        guarded.add(attr)
                    if not in_init:
                        writes.append((attr, node.lineno, under))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    continue
                visit(child, under)

        for stmt in getattr(fn, "body", []):
            visit(stmt, under)

    # --- module scope -----------------------------------------------------

    def _check_module_scope(self, rel: str,
                            tree: ast.Module) -> List[Finding]:
        module_names: Set[str] = set()
        for stmt in tree.body:
            for tgt in _assign_targets(stmt):
                if isinstance(tgt, ast.Name):
                    module_names.add(tgt.id)
                elif isinstance(tgt, ast.Tuple):
                    module_names.update(
                        e.id for e in tgt.elts if isinstance(e, ast.Name))

        writes: List[_Write] = []
        guarded: Set[str] = set()

        def scan_fn(fn: ast.AST, under0: bool = False) -> None:
            declared_global: Set[str] = set()

            def collect_globals(n: ast.AST) -> None:
                for child in ast.iter_child_nodes(n):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue  # nested fns declare their own globals
                    if isinstance(child, ast.Global):
                        declared_global.update(child.names)
                    collect_globals(child)

            collect_globals(fn)

            def visit(node: ast.AST, under: bool) -> None:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # lexical lock state: a closure defined under the lock
                    # is treated as running under it (same semantics as the
                    # class-scope walk)
                    scan_fn(node, under)
                    return
                if isinstance(node, ast.With):
                    locked = under or any(
                        _is_module_lock(item.context_expr)
                        for item in node.items)
                    for child in node.body:
                        visit(child, locked)
                    return
                for tgt in (_assign_targets(node)
                            if isinstance(node, ast.stmt) else []):
                    name = _module_name_written(tgt, module_names,
                                                declared_global)
                    if name is not None:
                        if under:
                            guarded.add(name)
                        writes.append((name, node.lineno, under))
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.expr):
                        continue
                    visit(child, under)

            for stmt in getattr(fn, "body", []):
                visit(stmt, under0)

        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_fn(stmt)
            elif isinstance(stmt, ast.ClassDef):
                # methods were handled by _check_class for self attrs; module
                # globals written from methods still count here
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        scan_fn(item)
        if not guarded:
            return []
        return [
            self.finding(rel, lineno,
                         f"module global `{name}` is written under a lock "
                         "elsewhere but written here without it")
            for name, lineno, under in writes
            if name in guarded and not under
        ]
