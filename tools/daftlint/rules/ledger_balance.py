"""DTL011 ledger-balance: every MemoryLedger charge to an inflight
account must reach a settle on ALL exits, including exception paths.

The charge/settle discipline (``exec_started``/``exec_done``,
``prefetch_started``/``prefetch_done``, …) is the engine's admission
accounting: an unsettled charge permanently shrinks the budget and
eventually wedges admission. PRs 9–16 each re-fixed a leak of this shape
by hand; this rule pins the discipline.

Flow-sensitive per function. A charge is balanced when one of:

- it sits inside a ``try`` whose ``finally`` performs a matching settle
  (the canonical idiom);
- the next statement is such a ``try`` (simple statements — assignments,
  bare expressions — may sit between the charge and the ``try``: they
  cannot transfer control);
- the charge line (or the comment line above) carries a cross-function
  escape annotation ``# daftlint: ledger-escape settled-by=f,g`` naming
  the function(s) that settle it — a done-callback, a worker-thread
  body, a drain path. The annotation is VERIFIED against the
  interprocedural model: every named function must exist and must call a
  matching settle, so a renamed or gutted settle path breaks the lint
  run instead of silently leaking.

Otherwise the rule distinguishes two failures: a settle later in the
same function on the fallthrough path only ("an exception between charge
and settle leaks the account") versus no settle at all.

The ``MemoryLedger`` class itself is exempt (its methods ARE the
accounting), as are parent-forwarding calls (``self._parent.X_started``
inside the ledger's own forwarding protocol). The ``cache`` account uses
a signed-delta API (``add``/``sub``) rather than a charge/settle pair
and is covered by its clamp logic at runtime, not by this rule.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..engine import Finding, Project, Rule
from ..interproc import LEDGER_PAIRS, model_for

ESCAPE_RE = re.compile(
    r"#\s*daftlint:\s*ledger-escape\s+settled-by=([A-Za-z0-9_.,\s]+)")

# statements that cannot transfer control between a charge and its try
_SIMPLE_STMTS = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                 ast.Pass, ast.Import, ast.ImportFrom, ast.Global,
                 ast.Nonlocal, ast.Delete)


def _calls_in(node: ast.AST) -> List[ast.Call]:
    """Calls within a statement, NOT descending into nested function or
    class bodies (those are analyzed as their own scopes)."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if n is not node and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _charge_of(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(method, receiver) when `call` is a ledger charge, else None."""
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in LEDGER_PAIRS):
        base = call.func.value
        recv = ""
        if isinstance(base, ast.Attribute):
            recv = base.attr
        elif isinstance(base, ast.Name):
            recv = base.id
        return call.func.attr, recv
    return None


def _settles_in(stmts: Sequence[ast.stmt], accepted: Set[str]) -> bool:
    for stmt in stmts:
        for call in _calls_in(stmt):
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr in accepted):
                return True
    return False


class LedgerBalanceRule(Rule):
    code = "DTL011"
    name = "ledger-balance"
    description = ("every MemoryLedger charge (*_started) must reach a "
                   "matching settle on all exits including exception "
                   "paths, or carry a verified ledger-escape annotation")

    def run(self, project: Project) -> List[Finding]:
        model = model_for(project)
        out: List[Finding] = []
        for rel in project.lint_files:
            tree = project.tree(rel)
            if tree is None:
                continue
            lines = project.source(rel).splitlines()
            self._walk(tree.body, rel, lines, cls=None, model=model,
                       out=out)
        return out

    # ---- scope walk -------------------------------------------------------

    def _walk(self, stmts: Sequence[ast.stmt], rel: str, lines: List[str],
              cls: Optional[str], model, out: List[Finding]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.ClassDef):
                # the ledger implementation is the accounting, not a user
                if stmt.name != "MemoryLedger":
                    self._walk(stmt.body, rel, lines, stmt.name, model, out)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_fn(stmt, rel, lines, model, out)
                self._walk(stmt.body, rel, lines, None, model, out)
            elif isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor,
                                   ast.With, ast.AsyncWith, ast.Try)):
                for body in self._bodies(stmt):
                    self._walk(body, rel, lines, cls, model, out)

    @staticmethod
    def _bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
        bodies = [getattr(stmt, "body", [])]
        bodies.append(getattr(stmt, "orelse", []))
        if isinstance(stmt, ast.Try):
            bodies.append(stmt.finalbody)
            for h in stmt.handlers:
                bodies.append(h.body)
        return bodies

    # ---- one function -----------------------------------------------------

    def _check_fn(self, fn: ast.AST, rel: str, lines: List[str],
                  model, out: List[Finding]) -> None:
        self._scan(fn, fn.body, rel, lines, frozenset(), model, out)

    def _scan(self, fn: ast.AST, stmts: Sequence[ast.stmt], rel: str,
              lines: List[str], fin_settles: frozenset,
              model, out: List[Finding]) -> None:
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, checked by _walk
            # compound statements: scan only the header expressions here
            # (their bodies are recursed into below — scanning the whole
            # subtree at every level would report nested charges once per
            # enclosing block)
            if isinstance(stmt, (ast.If, ast.While)):
                headers: List[ast.AST] = [stmt.test]
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                headers = [stmt.iter]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                headers = [item.context_expr for item in stmt.items]
            elif isinstance(stmt, ast.Try):
                headers = []
            elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
                headers = [stmt.subject]
            else:
                headers = [stmt]
            for call in [c for h in headers for c in _calls_in(h)]:
                ch = _charge_of(call)
                if ch is None:
                    continue
                meth, recv = ch
                if recv == "_parent":
                    continue  # ledger-internal forwarding
                accepted = set(LEDGER_PAIRS[meth])
                if accepted & fin_settles:
                    continue  # inside try with a settling finally
                if self._escape_ok(call, meth, accepted, rel, lines,
                                   model, out):
                    continue
                if self._next_try_settles(stmts, i, accepted):
                    continue
                if _settles_in(fn.body, accepted):
                    out.append(self.finding(
                        rel, call.lineno,
                        f"`{meth}` charge is settled on the normal path "
                        f"only — an exception between charge and settle "
                        f"leaks the account (wrap in try/finally or "
                        f"annotate `# daftlint: ledger-escape "
                        f"settled-by=...`)"))
                else:
                    out.append(self.finding(
                        rel, call.lineno,
                        f"`{meth}` charge is never settled in this "
                        f"function (no "
                        f"{'/'.join(sorted(accepted))} on any path; "
                        f"annotate `# daftlint: ledger-escape "
                        f"settled-by=...` if another function settles "
                        f"it)"))
            # descend, extending the finally-settle context through trys
            if isinstance(stmt, ast.Try):
                f2 = fin_settles
                found = {s.func.attr
                         for fin in stmt.finalbody
                         for s in _calls_in(fin)
                         if isinstance(s.func, ast.Attribute)}
                f2 = fin_settles | frozenset(found)
                for body in (stmt.body, stmt.orelse,
                             *[h.body for h in stmt.handlers]):
                    self._scan(fn, body, rel, lines, f2, model, out)
                self._scan(fn, stmt.finalbody, rel, lines, fin_settles,
                           model, out)
            elif isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor,
                                   ast.With, ast.AsyncWith)):
                for body in self._bodies(stmt):
                    self._scan(fn, body, rel, lines, fin_settles, model,
                               out)
            elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    self._scan(fn, case.body, rel, lines, fin_settles,
                               model, out)

    @staticmethod
    def _next_try_settles(stmts: Sequence[ast.stmt], i: int,
                          accepted: Set[str]) -> bool:
        """The charge-then-try idiom: the statement after the charge
        (skipping simple, non-control-transferring statements) is a try
        whose finally settles."""
        for nxt in stmts[i + 1:]:
            if isinstance(nxt, ast.Try):
                return _settles_in(nxt.finalbody, accepted)
            if not isinstance(nxt, _SIMPLE_STMTS):
                return False
        return False

    def _escape_ok(self, call: ast.Call, meth: str, accepted: Set[str],
                   rel: str, lines: List[str], model,
                   out: List[Finding]) -> bool:
        """True when the charge carries a ledger-escape annotation —
        verified or not. A stale annotation (naming a function that
        doesn't exist or doesn't settle) emits its own targeted finding
        here, which supersedes the generic charge-leak message: the fix
        is to repair the annotation, not to re-derive the flow."""
        names: List[str] = []
        for ln in (call.lineno, call.lineno - 1):
            if 0 < ln <= len(lines):
                m = ESCAPE_RE.search(lines[ln - 1])
                if m:
                    names = [n.strip() for n in m.group(1).split(",")
                             if n.strip()]
                    break
        if not names:
            return False
        for name in names:
            settlers = [
                k for k, fs in model.functions.items()
                if (fs["name"] == name.split(".")[-1]
                    and (name == fs["name"] or fs["qual"].endswith(name)
                         or fs["qual"] == name))
                and any(op["meth"] in accepted for op in fs["ledger"])]
            if not settlers:
                out.append(self.finding(
                    rel, call.lineno,
                    f"ledger-escape for `{meth}` names `{name}`, but no "
                    f"such function settles it "
                    f"({'/'.join(sorted(accepted))}) — stale "
                    f"annotation"))
        return True
