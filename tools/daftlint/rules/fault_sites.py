"""DTL004 fault-site coverage: the fault-injection registry and its call
sites must agree.

`daft_tpu/faults.py` declares the engine's fault sites in a module-level
`SITES` mapping (site name -> description). This rule cross-checks it
against every `faults.check(...)` call in the linted tree:

- a **registered site with no caller** is dead resilience surface — the
  site's recovery path can never be exercised;
- a **caller using an unregistered site** silently never fires (tests
  arming the registered name hit a different string than production code
  checks) — the exact class of bug the registry exists to prevent;
- a **non-literal site argument** cannot be statically verified and is
  flagged so the author either inlines the literal or suppresses with a
  reason.

The registry file is found by path suffix `faults.py`; if it exists but
declares no SITES mapping, that is itself a finding (the registry is the
contract). Projects without a faults.py (unit-test fixture trees) skip the
rule.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, Project, Rule, dotted_name


def _find_sites(tree: ast.Module) -> Optional[Tuple[Dict[str, int], int]]:
    """(site -> lineno, SITES lineno) from a module-level `SITES = {...}`
    dict/set/tuple/list of string constants; None when absent."""
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
        if not any(isinstance(t, ast.Name) and t.id == "SITES"
                   for t in targets):
            continue
        value = stmt.value
        keys: List[ast.expr] = []
        if isinstance(value, ast.Dict):
            keys = [k for k in value.keys if k is not None]
        elif isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            keys = list(value.elts)
        else:
            return {}, stmt.lineno
        out: Dict[str, int] = {}
        for k in keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = k.lineno
        return out, stmt.lineno
    return None


class FaultSiteCoverageRule(Rule):
    code = "DTL004"
    name = "fault-site-coverage"
    description = ("every registered fault site has a faults.check() caller "
                   "and no caller uses an unregistered site")

    def run(self, project: Project) -> List[Finding]:
        registry_rel = next(
            (r for r in project.files
             if r == "faults.py" or r.endswith("/faults.py")), None)
        if registry_rel is None:
            return []
        tree = project.tree(registry_rel)
        if tree is None:
            return []
        found = _find_sites(tree)
        if found is None:
            return [self.finding(
                registry_rel, 1,
                "no module-level `SITES` registry found — declare the fault "
                "sites so coverage can be checked")]
        sites, sites_line = found

        out: List[Finding] = []
        used: Dict[str, Tuple[str, int]] = {}
        for rel in project.files:
            if rel == registry_rel:
                continue
            ftree = project.tree(rel)
            if ftree is None:
                continue
            for node in ast.walk(ftree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                parts = name.split(".")
                # exact-segment match: `faults.check` / `x.faults.check`,
                # never `defaults.check`
                if len(parts) < 2 or parts[-1] != "check" or \
                        parts[-2] != "faults":
                    continue
                if not node.args:
                    out.append(self.finding(
                        rel, node.lineno, "faults.check() without a site"))
                    continue
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    site = arg.value
                    used.setdefault(site, (rel, node.lineno))
                    if site not in sites:
                        out.append(self.finding(
                            rel, node.lineno,
                            f"fault site `{site}` is not registered in "
                            "faults.SITES — injections armed at registered "
                            "names will never hit it"))
                else:
                    out.append(self.finding(
                        rel, node.lineno,
                        "non-literal fault site argument cannot be "
                        "statically checked against faults.SITES"))
        for site in sorted(set(sites) - set(used)):
            out.append(self.finding(
                registry_rel, sites.get(site, sites_line),
                f"registered fault site `{site}` has no faults.check() "
                "caller — its recovery path can never be exercised"))
        return out
