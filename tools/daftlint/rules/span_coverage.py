"""DTL006 span coverage: physical-operator execute() entry points must be
visible to the profiler.

The structured profiler (daft_tpu/profile/) gets per-op attribution two
ways: map-class ops route through ``self._map_execute`` (the driver's
pull/worker wrappers open their spans), and custom ``execute`` bodies open
phase spans around their internal blocking sections
(``ctx.stats.profiler.span(...)``). An op that does neither executes as a
blind spot — its fanout/build/merge work lands in whichever parent span
happened to be open, which is exactly the attribution gap the profiler
exists to close.

This rule mirrors DTL004's registry cross-check pattern: every class named
``*Op`` defining ``execute(self, inputs, ctx)`` (the physical-operator
signature) must, somewhere in that method body, either

- delegate to ``self._map_execute(...)`` (driver-instrumented), or
- open a profiler span (a ``.span(...)`` / ``.begin(...)`` call on a
  profiler object).

Since the morsel-driven streaming executor (daft_tpu/stream/) the rule
also pins the *morsel contract* and the stream driver's coverage:

- a class declaring ``morsel_streamable = True`` must define
  ``map_partition`` in the same class body — claiming streamability
  without the per-morsel entry point means the driver would silently fall
  back to whole-partition materialization inside a streaming stage;
- the stream driver's producer entry point (a function named
  ``_produce_partition``) must itself open a profiler span, so morsel
  work is never an attribution blind spot on the pool workers.

Pre-existing uncovered ops are grandfathered via baseline.json (the
DTL004 discipline: the backlog is visible, new blind spots fail the run).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, Project, Rule, dotted_name

# sanctioned span-opening attribute names on a call, e.g.
# ctx.stats.profiler.span(...), prof.begin(...)
_SPAN_ATTRS = {"span", "begin"}


def _execute_is_covered(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        parts = name.split(".")
        if parts[-1] == "_map_execute":
            return True
        if parts[-1] in _SPAN_ATTRS and len(parts) >= 2:
            # require a profiler-ish receiver so str.span()-style helpers
            # never count as coverage: ...profiler.span(...) or a local
            # bound to one (prof.span / profiler.begin)
            recv = parts[-2]
            if recv in ("profiler", "prof") or "profiler" in parts:
                return True
    return False


def _is_physical_execute(fn: ast.FunctionDef) -> bool:
    args = [a.arg for a in fn.args.args]
    if not (len(args) >= 3 and args[0] == "self" and args[1] == "inputs"):
        return False
    # skip abstract stubs (docstring + raise/pass only) — the base class
    # contract, not an entry point
    body = [n for n in fn.body
            if not (isinstance(n, ast.Expr)
                    and isinstance(n.value, ast.Constant))]
    return not all(isinstance(n, (ast.Raise, ast.Pass)) for n in body)


# stream-driver producer entry points (daft_tpu/stream/pipeline.py): each
# runs morsel work on a pool worker and must open its own profiler span —
# or delegate to another function in this set that does (the retry wrapper
# chain _produce_partition -> _produce_with_retry -> _produce_once)
_STREAM_DRIVER_FNS = {"_produce_partition", "_produce_with_retry",
                      "_produce_once"}

# distributed-worker task entry point (daft_tpu/dist/worker.py): every
# remote task execution must open a task-scope span — it is the root the
# driver splices the worker's telemetry subtree under (obs/cluster.py),
# and without it the whole worker becomes a cluster-wide attribution
# blind spot exactly when queries get hardest to debug
_WORKER_TASK_FNS = {"_execute_task"}

# dynamic-batching apply entry point (daft_tpu/batch/executor.py): every
# coalesced batch runs through here, and its "batch.coalesce"/"actor.apply"
# spans are what parent batched-UDF work to the causing op — without them
# batched inference is a per-batch attribution blind spot
_BATCH_EXEC_FNS = {"_run_flush"}

# resident-segment executor entry point (daft_tpu/execution.py): every
# DeviceSegmentOp partition routes through here, and its "fuse.segment"
# span — parented to the driving op, zero orphans — is what attributes
# whole-segment resident execution (stage + map + agg + gather as ONE
# phase) in the merged trace
_SEGMENT_EXEC_FNS = {"eval_segment"}


def _delegates_to_stream_driver(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None \
                    and name.split(".")[-1] in _STREAM_DRIVER_FNS:
                return True
    return False


def _claims_morsel_streamable(cls: ast.ClassDef) -> bool:
    # both `morsel_streamable = True` and the annotated
    # `morsel_streamable: bool = True` — the runtime getattr sees either
    for item in cls.body:
        if isinstance(item, ast.Assign):
            targets = item.targets
        elif isinstance(item, ast.AnnAssign) and item.value is not None:
            targets = [item.target]
        else:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name) \
                    and tgt.id == "morsel_streamable" \
                    and isinstance(item.value, ast.Constant) \
                    and item.value.value is True:
                return True
    return False


class SpanCoverageRule(Rule):
    code = "DTL006"
    name = "span-coverage"
    description = ("every *Op.execute(self, inputs, ctx) entry point "
                   "delegates to _map_execute or opens a profiler span; "
                   "morsel_streamable ops implement map_partition; the "
                   "stream driver's producer, the distributed worker's "
                   "task entry point, and the resident-segment executor "
                   "open spans")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for rel in project.lint_files:
            tree = project.tree(rel)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.FunctionDef) \
                        and node.name in _STREAM_DRIVER_FNS:
                    if not (_execute_is_covered(node)
                            or _delegates_to_stream_driver(node)):
                        out.append(self.finding(
                            rel, node.lineno,
                            f"stream-driver `{node.name}` opens no "
                            "profiler span — morsel work on pool workers "
                            "must not be an attribution blind spot"))
                    continue
                if isinstance(node, ast.FunctionDef) \
                        and node.name in _WORKER_TASK_FNS:
                    if not _execute_is_covered(node):
                        out.append(self.finding(
                            rel, node.lineno,
                            f"worker task entry `{node.name}` opens no "
                            "task-scope profiler span — remote work "
                            "would vanish from the merged cluster trace"))
                    continue
                if isinstance(node, ast.FunctionDef) \
                        and node.name in _BATCH_EXEC_FNS:
                    if not _execute_is_covered(node):
                        out.append(self.finding(
                            rel, node.lineno,
                            f"batch-executor entry `{node.name}` opens no "
                            "profiler span — coalesced batch applies must "
                            "carry batch.coalesce/actor.apply attribution"))
                    continue
                if isinstance(node, ast.FunctionDef) \
                        and node.name in _SEGMENT_EXEC_FNS:
                    if not _execute_is_covered(node):
                        out.append(self.finding(
                            rel, node.lineno,
                            f"segment-executor entry `{node.name}` opens "
                            "no profiler span — HBM-resident segment "
                            "execution must carry fuse.segment attribution"))
                    continue
                if not isinstance(node, ast.ClassDef) or \
                        not node.name.endswith("Op"):
                    continue
                methods = {item.name for item in node.body
                           if isinstance(item, ast.FunctionDef)}
                if _claims_morsel_streamable(node) \
                        and "map_partition" not in methods:
                    out.append(self.finding(
                        rel, node.lineno,
                        f"`{node.name}` claims `morsel_streamable = True` "
                        "but defines no `map_partition` — the streaming "
                        "driver would silently materialize whole "
                        "partitions inside a streaming stage"))
                for item in node.body:
                    if not isinstance(item, ast.FunctionDef) or \
                            item.name != "execute":
                        continue
                    if not _is_physical_execute(item):
                        continue
                    if _execute_is_covered(item):
                        continue
                    out.append(self.finding(
                        rel, item.lineno,
                        f"`{node.name}.execute` opens no profiler span — "
                        "route through `self._map_execute` or wrap its "
                        "blocking phases in "
                        "`ctx.stats.profiler.span(...)`"))
        return out
