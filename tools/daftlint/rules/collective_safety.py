"""DTL003 collective-safety: `jax.lax` collectives name their axis and are
reachable only through breaker-guarded wrappers.

Two checks:

1. **axis-named**: every `lax.<collective>` / `jax.lax.<collective>` call in
   files under daft_tpu/parallel/ passes the axis explicitly (second
   positional argument or `axis_name=`/`axis=` keyword). A collective
   without an axis name compiles against whatever axis is ambient — silent
   mis-reduction when meshes nest.

2. **breaker-guarded reachability**: a top-level function whose body
   (nested defs included) invokes a collective is a *bearing* function
   (e.g. `build_exchange`). Every CALL to a bearing function, anywhere in
   the linted tree, must sit in a call chain that passes through a
   breaker-guarded function — one whose body calls `<breaker>.allow(...)`
   (the DeviceHealth gate). Safety is computed as a fixpoint over the
   name-based call graph: a caller is safe if it is guarded itself or if
   every one of ITS call sites is safe; an unguarded entry point with no
   callers is a finding (nothing stops a future caller skipping the
   breaker). Calls between functions within the same collectives module are
   exempt (that module IS the primitive layer).

Since the interprocedural engine landed, the raw facts (collective calls,
guard calls, call sites, top-level grouping) come from the shared
per-file summaries (tools/daftlint/interproc.py) instead of a private
AST walk — the semantics above are unchanged, and the name-keyed
deliberately-coarse `safe()` fixpoint is kept verbatim: DTL003's
contract is "every same-named caller anywhere must be guarded", stricter
on purpose than the model's resolved call graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, Project, Rule
from ..interproc import (COLLECTIVES, _collective_call, _has_axis,  # noqa: F401
                         model_for)


class CollectiveSafetyRule(Rule):
    code = "DTL003"
    name = "collective-safety"
    description = ("jax.lax collectives must name an explicit axis and be "
                   "reachable only via breaker-guarded wrappers")

    def run(self, project: Project) -> List[Finding]:
        model = model_for(project)
        out: List[Finding] = []
        parallel_files = [r for r in project.files
                          if "parallel" in r.split("/")[:-1]]

        # -- check 1: axis named, and find bearing top-level functions
        bearing: Dict[str, str] = {}  # fn name -> defining file
        for rel in parallel_files:
            s = model.summaries.get(rel)
            if s is None:
                continue
            for fsum in s["functions"].values():
                for cname, line, has_axis in fsum["collectives"]:
                    if not has_axis:
                        out.append(self.finding(
                            rel, line,
                            f"collective `{cname}` without an explicit "
                            "axis_name"))
                if fsum["collectives"] and fsum["top"] is not None:
                    bearing[fsum["top"]] = rel
        if not bearing:
            return out

        # -- check 2: every call to a bearing function is breaker-guarded.
        # Name-keyed call graph over top-level functions, from summaries.
        guarded: Set[str] = set()
        call_sites: Dict[str, List[Tuple[str, Optional[str], int]]] = {}
        #   callee name -> [(file, enclosing top-level fn name or None, line)]
        for rel in project.files:
            s = model.summaries.get(rel)
            if s is None:
                continue
            for fsum in s["functions"].values():
                if fsum["guard"] and fsum["top"] is not None:
                    guarded.add(fsum["top"])
                for site in fsum["calls"]:
                    if site["recv"] == "?":
                        continue  # computed receiver: never a name match
                    call_sites.setdefault(site["name"], []).append(
                        (rel, fsum["top"], site["line"]))

        safe_memo: Dict[str, bool] = {}

        def safe(fname: Optional[str], stack: Set[str]) -> bool:
            if fname is None:
                return False  # module-level call: nothing guards it
            if fname in guarded:
                return True
            if fname in safe_memo:
                return safe_memo[fname]
            if fname in stack:
                return False  # cycle without a guard anywhere on it
            sites = call_sites.get(fname, [])
            if not sites:
                safe_memo[fname] = False  # unguarded entry point
                return False
            stack.add(fname)
            ok = all(safe(caller, stack) for _rel, caller, _ln in sites)
            stack.discard(fname)
            safe_memo[fname] = ok
            return ok

        for bname, bfile in sorted(bearing.items()):
            for rel, caller, line in call_sites.get(bname, []):
                if rel == bfile:
                    continue  # intra-module calls in the primitive layer
                if not safe(caller, set()):
                    where = f"`{caller}`" if caller else "module level"
                    out.append(self.finding(
                        rel, line,
                        f"call to collective-bearing `{bname}` from {where} "
                        "is not reachable through a breaker-guarded wrapper "
                        "(.allow() gate)"))
        return out
