"""DTL003 collective-safety: `jax.lax` collectives name their axis and are
reachable only through breaker-guarded wrappers.

Two checks:

1. **axis-named**: every `lax.<collective>` / `jax.lax.<collective>` call in
   files under daft_tpu/parallel/ passes the axis explicitly (second
   positional argument or `axis_name=`/`axis=` keyword). A collective
   without an axis name compiles against whatever axis is ambient — silent
   mis-reduction when meshes nest.

2. **breaker-guarded reachability**: a top-level function whose body
   (nested defs included) invokes a collective is a *bearing* function
   (e.g. `build_exchange`). Every CALL to a bearing function, anywhere in
   the linted tree, must sit in a call chain that passes through a
   breaker-guarded function — one whose body calls `<breaker>.allow(...)`
   (the DeviceHealth gate). Safety is computed as a fixpoint over the
   name-based call graph: a caller is safe if it is guarded itself or if
   every one of ITS call sites is safe; an unguarded entry point with no
   callers is a finding (nothing stops a future caller skipping the
   breaker). Calls between functions within the same collectives module are
   exempt (that module IS the primitive layer).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Finding, Project, Rule, dotted_name

COLLECTIVES = {"all_to_all", "psum", "pmax", "pmin", "pmean", "all_gather",
               "ppermute", "pshuffle", "pbroadcast", "psum_scatter"}
_AXIS_KEYWORDS = {"axis_name", "axis"}


def _collective_call(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[-1] in COLLECTIVES and (
            len(parts) == 1 or parts[-2] == "lax" or parts[0] in ("jax", "lax")):
        return name
    return None


def _has_axis(node: ast.Call) -> bool:
    if len(node.args) >= 2:
        return True
    return any(kw.arg in _AXIS_KEYWORDS for kw in node.keywords)


def _top_level_functions(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """(qualified name, node) for module functions and class methods."""
    out: List[Tuple[str, ast.AST]] = []
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((stmt.name, stmt))
        elif isinstance(stmt, ast.ClassDef):
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append((item.name, item))
    return out


def _contains_guard(fn: ast.AST) -> bool:
    """Does the function body call `<something>.allow(...)` (the breaker)?"""
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "allow"):
            return True
    return False


class CollectiveSafetyRule(Rule):
    code = "DTL003"
    name = "collective-safety"
    description = ("jax.lax collectives must name an explicit axis and be "
                   "reachable only via breaker-guarded wrappers")

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        parallel_files = [r for r in project.files
                          if "parallel" in r.split("/")[:-1]]

        # -- check 1: axis named, and find bearing top-level functions
        bearing: Dict[str, str] = {}  # fn name -> defining file
        for rel in parallel_files:
            tree = project.tree(rel)
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Call):
                    cname = _collective_call(node)
                    if cname is not None and not _has_axis(node):
                        out.append(self.finding(
                            rel, node.lineno,
                            f"collective `{cname}` without an explicit "
                            "axis_name"))
            for fname, fn in _top_level_functions(tree):
                if any(isinstance(n, ast.Call) and _collective_call(n)
                       for n in ast.walk(fn)):
                    bearing[fname] = rel
        if not bearing:
            return out

        # -- check 2: every call to a bearing function is breaker-guarded.
        # Build a project-wide name-keyed call graph over top-level functions.
        guarded: Set[str] = set()
        call_sites: Dict[str, List[Tuple[str, Optional[str], int]]] = {}
        #   callee name -> [(file, enclosing top-level fn name or None, line)]
        for rel in project.files:
            tree = project.tree(rel)
            if tree is None:
                continue
            fns = _top_level_functions(tree)
            for fname, fn in fns:
                if _contains_guard(fn):
                    guarded.add(fname)
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        callee = self._callee_name(node)
                        if callee is not None:
                            call_sites.setdefault(callee, []).append(
                                (rel, fname, node.lineno))
            # module-level call sites (outside any function)
            in_fn = set()
            for _fname, fn in fns:
                in_fn.update(id(n) for n in ast.walk(fn))
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) and id(node) not in in_fn:
                    callee = self._callee_name(node)
                    if callee is not None:
                        call_sites.setdefault(callee, []).append(
                            (rel, None, node.lineno))

        safe_memo: Dict[str, bool] = {}

        def safe(fname: Optional[str], stack: Set[str]) -> bool:
            if fname is None:
                return False  # module-level call: nothing guards it
            if fname in guarded:
                return True
            if fname in safe_memo:
                return safe_memo[fname]
            if fname in stack:
                return False  # cycle without a guard anywhere on it
            sites = call_sites.get(fname, [])
            if not sites:
                safe_memo[fname] = False  # unguarded entry point
                return False
            stack.add(fname)
            ok = all(safe(caller, stack) for _rel, caller, _ln in sites)
            stack.discard(fname)
            safe_memo[fname] = ok
            return ok

        for bname, bfile in sorted(bearing.items()):
            for rel, caller, line in call_sites.get(bname, []):
                if rel == bfile:
                    continue  # intra-module calls in the primitive layer
                if not safe(caller, set()):
                    where = f"`{caller}`" if caller else "module level"
                    out.append(self.finding(
                        rel, line,
                        f"call to collective-bearing `{bname}` from {where} "
                        "is not reachable through a breaker-guarded wrapper "
                        "(.allow() gate)"))
        return out

    @staticmethod
    def _callee_name(node: ast.Call) -> Optional[str]:
        name = dotted_name(node.func)
        if name is None:
            return None
        return name.split(".")[-1]
