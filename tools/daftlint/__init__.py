"""daftlint: pluggable AST-based invariant lints for the daft_tpu engine.

The type system cannot see the conventions PR 1's resilience layer depends
on — jit-traced kernels staying pure, lock-guarded state staying guarded,
collectives staying breaker-wrapped and axis-named, fault sites staying
registered and covered, migrated modules staying on the typed error
hierarchy. daftlint machine-checks them: an engine (`engine.py`) with a
`Rule` protocol, per-file AST cache, `# daftlint: disable=RULE`
suppressions, a committed baseline for grandfathered findings, and text +
JSON output; five rules under `rules/` encode the invariants (DTL001–DTL005).

Run it:

    python -m tools.daftlint               # lint daft_tpu/, exit 1 on new findings
    python -m tools.daftlint --json        # machine-readable report
    python -m tools.daftlint --list-rules  # rule table

Adding an invariant is ~50 lines: subclass `Rule` in a module under
`rules/`, yield `Finding`s from `run()`, and append it to `rules.ALL_RULES`.
"""

from .engine import (Finding, LintResult, Project, Rule, load_baseline,
                     render_json, render_text, run_lint, write_baseline)
from .rules import ALL_RULES

__all__ = [
    "ALL_RULES", "Finding", "LintResult", "Project", "Rule",
    "load_baseline", "render_json", "render_text", "run_lint",
    "write_baseline",
]
