"""daftlint engine: Project (per-file AST cache), Rule protocol, suppression
comments, baseline handling, and text/JSON rendering.

Contracts:

- A `Finding` is identified for baseline purposes by ``rule:path:message``
  (line numbers excluded, so unrelated edits that shift lines don't churn
  the baseline).
- ``# daftlint: disable=DTL001`` on a line suppresses that line's findings
  for the named rule(s); on a comment-only line it suppresses the NEXT
  line. ``disable=all`` suppresses every rule. Comma-separate for several.
- The committed baseline (``tools/daftlint/baseline.json``) grandfathers
  findings: they still appear in reports (flagged ``baselined``) but do not
  fail the run. Only NEW findings exit nonzero.
- Files that fail to parse produce a single DTL000 finding rather than
  crashing the run.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set

PARSE_ERROR_RULE = "DTL000"


@dataclass(frozen=True)
class Finding:
    """One lint violation. `path` is a posix relpath from the project root."""

    rule: str
    path: str
    line: int
    message: str
    baselined: bool = False

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "baselined": self.baselined}


class Rule:
    """A lint rule. Subclasses set `code`/`name`/`description` and implement
    `run(project)`, returning Findings. Rules are project-level (not
    per-file) so cross-file invariants (fault-site coverage, collective
    reachability) are first-class; per-file rules just loop project.files."""

    code: str = ""
    name: str = ""
    description: str = ""

    def run(self, project: "Project") -> List[Finding]:
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str) -> Finding:
        return Finding(self.code, path, line, message)


class Project:
    """The file set under lint, with cached sources and ASTs (each file is
    read and parsed at most once per run, however many rules inspect it)."""

    def __init__(self, root: str, files: Sequence[str]):
        self.root = os.path.abspath(root)
        self.files: List[str] = sorted(
            p.replace(os.sep, "/") for p in files)
        # the focus set per-file rules REPORT on. Defaults to everything;
        # --changed-only narrows it to the git-dirty subset while
        # project-wide rules (call graph, lock order, fault coverage)
        # still analyze all of `files` — summaries for unchanged files
        # come from the content-hash cache, so the narrow run stays fast
        self.lint_files: List[str] = self.files
        self._sources: Dict[str, str] = {}
        self._trees: Dict[str, Optional[ast.Module]] = {}
        self.parse_errors: List[Finding] = []

    def focus(self, files: Sequence[str]) -> None:
        """Narrow the reporting set (``--changed-only``). Unknown paths are
        ignored so a deleted-but-still-dirty file can't crash the run."""
        want = {p.replace(os.sep, "/") for p in files}
        self.lint_files = [p for p in self.files if p in want]

    @classmethod
    def discover(cls, root: str,
                 subdirs: Sequence[str] = ("daft_tpu",)) -> "Project":
        """All .py files under root/<subdir> for each subdir (a subdir may
        also be a single .py file)."""
        root = os.path.abspath(root)
        files: List[str] = []
        for sub in subdirs:
            base = os.path.join(root, sub)
            if os.path.isfile(base):
                files.append(os.path.relpath(base, root))
                continue
            for dirpath, _dirnames, filenames in os.walk(base):
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(
                            os.path.relpath(os.path.join(dirpath, fn), root))
        return cls(root, files)

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            with open(os.path.join(self.root, rel), "r",
                      encoding="utf-8") as f:
                self._sources[rel] = f.read()
        return self._sources[rel]

    def tree(self, rel: str) -> Optional[ast.Module]:
        """Parsed AST, or None when the file has a syntax error (recorded
        once as a DTL000 finding)."""
        if rel not in self._trees:
            try:
                self._trees[rel] = ast.parse(self.source(rel), filename=rel)
            except SyntaxError as e:
                self._trees[rel] = None
                self.parse_errors.append(Finding(
                    PARSE_ERROR_RULE, rel, e.lineno or 1,
                    f"syntax error: {e.msg}"))
        return self._trees[rel]


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*daftlint:\s*disable=([A-Za-z0-9_,\s-]+)")


def suppressions(source: str) -> Dict[int, Set[str]]:
    """line number -> set of suppressed rule codes (or {'all'}). A marker on
    a code line covers that line; on a comment-only line it covers the next
    line (so a suppression can sit above the construct it excuses)."""
    out: Dict[int, Set[str]] = {}
    for i, raw in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(raw)
        if m is None:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        target = i + 1 if raw.split("#", 1)[0].strip() == "" else i
        out.setdefault(target, set()).update(codes)
    return out


def _is_suppressed(f: Finding, per_file: Dict[str, Dict[int, Set[str]]],
                   project: Project) -> bool:
    if f.path not in per_file:
        try:
            per_file[f.path] = suppressions(project.source(f.path))
        except OSError:
            per_file[f.path] = {}
    codes = per_file[f.path].get(f.line)
    return bool(codes) and (f.rule in codes or "all" in codes)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[str]) -> Dict[str, dict]:
    """key -> entry ({rule, path, message, count, comment?}). Missing file
    => {}. `count` is how many occurrences of the key are grandfathered
    (duplicate entries in the file accumulate; an entry may also carry an
    explicit count) — a NEW duplicate of a baselined violation must still
    fail the run, so run_lint consumes the budget per occurrence."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[str, dict] = {}
    for entry in data.get("findings", []):
        key = f"{entry['rule']}:{entry['path']}:{entry['message']}"
        n = int(entry.get("count", 1))
        if key in out:
            out[key]["count"] += n
        else:
            out[key] = dict(entry)
            out[key]["count"] = n
    return out


def write_baseline(path: str, findings: Iterable[Finding],
                   comments: Optional[Dict[str, str]] = None) -> None:
    """Write the baseline for the given findings; `comments` maps finding
    keys to the why-kept note the ISSUE requires for grandfathered entries."""
    comments = comments or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line)):
        e = {"rule": f.rule, "path": f.path, "message": f.message}
        if f.key in comments:
            e["comment"] = comments[f.key]
        entries.append(e)
    with open(path, "w", encoding="utf-8") as fp:
        json.dump({"version": 1, "findings": entries}, fp, indent=2,
                  sort_keys=True)
        fp.write("\n")


# ---------------------------------------------------------------------------
# run + rendering
# ---------------------------------------------------------------------------

@dataclass
class LintResult:
    findings: List[Finding]      # new + baselined, suppressed removed
    new: List[Finding]
    baselined: List[Finding]
    suppressed_count: int
    files_scanned: int

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0


def run_lint(project: Project, rules: Sequence[Rule],
             baseline: Optional[Dict[str, dict]] = None) -> LintResult:
    baseline = baseline or {}
    # parse the focus set up front: a syntax-broken file must surface as
    # DTL000 even when the rule set under run never touches its AST
    for rel in project.lint_files:
        project.tree(rel)
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.run(project))
    raw.extend(project.parse_errors)
    if project.lint_files is not project.files:
        # focused run (--changed-only): project-wide analyses still saw
        # the whole tree, but findings are REPORTED only for the focus
        # set — an unchanged file's backlog is the full run's business
        focus = set(project.lint_files)
        raw = [f for f in raw if f.path in focus]
    per_file: Dict[str, Dict[int, Set[str]]] = {}
    kept: List[Finding] = []
    suppressed = 0
    # per-occurrence baseline budget: the Nth duplicate of a baselined
    # violation beyond its grandfathered count is NEW and fails the run
    budget = {k: e.get("count", 1) for k, e in baseline.items()}
    for f in raw:
        if _is_suppressed(f, per_file, project):
            suppressed += 1
            continue
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            f = Finding(f.rule, f.path, f.line, f.message, baselined=True)
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    new = [f for f in kept if not f.baselined]
    old = [f for f in kept if f.baselined]
    return LintResult(kept, new, old, suppressed, len(project.files))


def render_text(result: LintResult, rules: Sequence[Rule]) -> str:
    lines = []
    for f in result.findings:
        tag = " [baselined]" if f.baselined else ""
        lines.append(f"{f.path}:{f.line}: {f.rule} {f.message}{tag}")
    lines.append(
        f"daftlint: {len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed_count} suppressed "
        f"({result.files_scanned} files, {len(rules)} rules)")
    return "\n".join(lines)


def render_json(result: LintResult, rules: Sequence[Rule],
                root: str) -> str:
    """The documented JSON schema (see README 'Static analysis'):

    {
      "version": 1, "tool": "daftlint", "root": "<abs path>",
      "rules":    [{"code", "name", "description"}, ...],
      "counts":   {"files", "total", "new", "baselined", "suppressed"},
      "findings": [{"rule", "path", "line", "message", "baselined"}, ...]
    }
    """
    doc = {
        "version": 1,
        "tool": "daftlint",
        "root": os.path.abspath(root),
        "rules": [{"code": r.code, "name": r.name,
                   "description": r.description} for r in rules],
        "counts": {
            "files": result.files_scanned,
            "total": len(result.findings),
            "new": len(result.new),
            "baselined": len(result.baselined),
            "suppressed": result.suppressed_count,
        },
        "findings": [f.as_dict() for f in result.findings],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def render_sarif(result: LintResult, rules: Sequence[Rule],
                 root: str) -> str:
    """SARIF 2.1.0 (the interchange format CI annotators ingest). One run,
    one result per finding; baselined findings carry an ``external``
    suppression so viewers show them greyed-out rather than as regressions.
    New findings are ``error`` level — they fail the run — baselined ones
    ``warning``."""
    by_code: Dict[str, int] = {}
    rule_objs = []
    for i, r in enumerate(rules):
        by_code[r.code] = i
        rule_objs.append({
            "id": r.code,
            "name": r.name,
            "shortDescription": {"text": r.description},
        })
    results = []
    for f in result.findings:
        entry: dict = {
            "ruleId": f.rule,
            "level": "warning" if f.baselined else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "PROJECTROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if f.rule in by_code:
            entry["ruleIndex"] = by_code[f.rule]
        if f.baselined:
            entry["suppressions"] = [{"kind": "external"}]
        results.append(entry)
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "daftlint",
                "informationUri": "https://github.com/daft-tpu",
                "rules": rule_objs,
            }},
            "originalUriBaseIds": {
                "PROJECTROOT": {"uri": "file://" + os.path.abspath(root)
                                + "/"},
            },
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


# ---------------------------------------------------------------------------
# shared AST helpers used by several rules
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
