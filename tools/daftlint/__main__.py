"""CLI: ``python -m tools.daftlint [paths...] [--json] [--baseline FILE]``.

Exits 0 when the tree is clean (modulo baseline), 1 on new findings, 2 on
usage errors. ``--write-baseline`` rewrites the baseline from the current
findings (for grandfathering a just-added rule's backlog — each kept entry
should gain a ``comment`` explaining why it stays).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from .engine import (Project, load_baseline, render_json, render_text,
                     run_lint, write_baseline)
from .rules import ALL_RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="daftlint",
        description="AST invariant lints for the daft_tpu engine "
                    "(DTL001-DTL005)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="directories/files to lint, relative to --root "
                         "(default: daft_tpu)")
    ap.add_argument("--root", default=None,
                    help="project root (default: the repo containing this "
                         "tool)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable JSON report")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file for grandfathered findings "
                         f"(default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline from the current findings and "
                         "exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.code}  {r.name:22s} {r.description}")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    subdirs = args.paths or ["daft_tpu"]
    # a typo'd path must not green-light CI by linting nothing
    missing = [s for s in subdirs
               if not os.path.exists(os.path.join(root, s))]
    if missing:
        print(f"daftlint: path(s) not found under {root}: "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2
    project = Project.discover(root, subdirs)
    if not project.files:
        print(f"daftlint: no python files found under {root} "
              f"({', '.join(subdirs)})", file=sys.stderr)
        return 2
    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    result = run_lint(project, ALL_RULES, baseline)

    if args.write_baseline:
        # comments come from the FILE, not the in-memory dict: with
        # --no-baseline the dict is empty and the why-kept notes every
        # grandfathered entry must carry would be silently dropped
        existing = load_baseline(args.baseline)
        comments = {k: e["comment"] for k, e in existing.items()
                    if "comment" in e}
        write_baseline(args.baseline, result.findings, comments)
        print(f"daftlint: baseline written to {args.baseline} "
              f"({len(result.findings)} finding(s))")
        return 0

    if args.as_json:
        print(render_json(result, ALL_RULES, root))
    else:
        print(render_text(result, ALL_RULES))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
