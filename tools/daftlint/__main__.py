"""CLI: ``python -m tools.daftlint [paths...] [--json] [--sarif FILE]
[--changed-only] [--jobs N] [--baseline FILE]``.

Exits 0 when the tree is clean (modulo baseline), 1 on new findings, 2 on
usage errors. ``--write-baseline`` rewrites the baseline from the current
findings (for grandfathering a just-added rule's backlog — each kept entry
should gain a ``comment`` explaining why it stays).

``--changed-only`` narrows per-file reporting to the git-dirty subset
(unstaged + staged + untracked) while project-wide analyses (call graph,
lock order, fault-site coverage) still see the whole tree — per-file
summaries for unchanged files come from the content-hash cache, so the
pre-commit path stays fast as the engine grows.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

from .engine import (Project, load_baseline, render_json, render_sarif,
                     render_text, run_lint, write_baseline)
from .interproc import SummaryCache
from .rules import ALL_RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _git_changed_files(root: str) -> Optional[List[str]]:
    """Repo-relative paths that differ from HEAD (worktree + index) plus
    untracked files, or None when git is unavailable."""
    out: List[str] = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "diff", "--name-only", "--cached"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if res.returncode != 0:
            return None
        out.extend(ln.strip() for ln in res.stdout.splitlines()
                   if ln.strip())
    return sorted(set(out))


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="daftlint",
        description="AST + interprocedural invariant lints for the "
                    "daft_tpu engine (DTL001-DTL012)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="directories/files to lint, relative to --root "
                         "(default: daft_tpu)")
    ap.add_argument("--root", default=None,
                    help="project root (default: the repo containing this "
                         "tool)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable JSON report")
    ap.add_argument("--sarif", metavar="FILE", default=None,
                    help="also write a SARIF 2.1.0 report to FILE "
                         "('-' for stdout instead of the text report)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only on files changed vs git HEAD "
                         "(project-wide analyses still see the whole "
                         "tree); exits 0 when nothing relevant changed")
    ap.add_argument("--jobs", type=int, default=0, metavar="N",
                    help="parallel per-file summarization workers "
                         "(0 = serial; 'auto' sizing is min(8, cpus))")
    ap.add_argument("--cache", metavar="FILE", default=None,
                    help="summary-cache path (default: "
                         "<root>/.daftlint-cache.json)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the per-file summary cache")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file for grandfathered findings "
                         f"(default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline from the current findings and "
                         "exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.code}  {r.name:22s} {r.description}")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    subdirs = args.paths or ["daft_tpu"]
    # a typo'd path must not green-light CI by linting nothing
    missing = [s for s in subdirs
               if not os.path.exists(os.path.join(root, s))]
    if missing:
        print(f"daftlint: path(s) not found under {root}: "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2
    project = Project.discover(root, subdirs)
    if not project.files:
        print(f"daftlint: no python files found under {root} "
              f"({', '.join(subdirs)})", file=sys.stderr)
        return 2

    if not args.no_cache:
        cache_path = args.cache or os.path.join(root,
                                                ".daftlint-cache.json")
        project.summary_cache = SummaryCache(cache_path)
    if args.jobs:
        project.summary_jobs = max(0, args.jobs)

    if args.changed_only:
        changed = _git_changed_files(root)
        if changed is None:
            print("daftlint: --changed-only needs git; linting the full "
                  "tree", file=sys.stderr)
        else:
            project.focus(changed)
            if not project.lint_files:
                print("daftlint: no linted files changed vs HEAD "
                      f"({len(project.files)} files tracked)")
                return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    result = run_lint(project, ALL_RULES, baseline)

    if args.write_baseline:
        # comments come from the FILE, not the in-memory dict: with
        # --no-baseline the dict is empty and the why-kept notes every
        # grandfathered entry must carry would be silently dropped
        existing = load_baseline(args.baseline)
        comments = {k: e["comment"] for k, e in existing.items()
                    if "comment" in e}
        write_baseline(args.baseline, result.findings, comments)
        print(f"daftlint: baseline written to {args.baseline} "
              f"({len(result.findings)} finding(s))")
        return 0

    if args.sarif == "-":
        print(render_sarif(result, ALL_RULES, root))
    else:
        if args.sarif:
            with open(args.sarif, "w", encoding="utf-8") as f:
                f.write(render_sarif(result, ALL_RULES, root))
                f.write("\n")
        if args.as_json:
            print(render_json(result, ALL_RULES, root))
        else:
            print(render_text(result, ALL_RULES))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
