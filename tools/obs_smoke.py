"""obs-smoke: run queries with the flight recorder in every mode and
validate each surface end to end. Wired into `make lint` (and usable alone
via `make obs-smoke`) so a schema regression in the QueryRecord, the
health snapshot, the diagnostics bundles, or the health gauges fails the
static-gate path before any production consumer trips over it.

Checks, in order:
 1. a plain collect() appends a QueryRecord that passes validate_record,
    with outcome "ok", a plan fingerprint, and df.last_query_record()
    identity with the log entry;
 2. daft_tpu.health() passes validate_health and names both breaker kinds;
 3. a forced slow query (threshold 0 + diagnostics_dir) writes a bundle
    containing record.json (valid) + stats.txt, and the SECOND run of the
    same plan fingerprint is auto-profiled (bundle carries profile.json);
 4. metrics_text() exports the health/ledger gauges;
 5. the structured-log ring carries the bundle's info line with query_id;
 6. DISTRIBUTED leg: a 2-worker profiled query produces ONE merged
    QueryProfile that validates with zero orphan spans, carries at least
    one spliced span per worker process (the chrome per-worker lanes),
    stamps driver-side dist.remote spans, and leaves zero orphan worker
    log lines in the driver's ring — the cluster observability plane's
    schema gate (daft_tpu/obs/cluster.py).

Exits nonzero with a named failure on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.obs import log as obs_log
    from daft_tpu.obs.capture import list_bundles
    from daft_tpu.obs.health import validate_health
    from daft_tpu.obs.querylog import validate_record

    dt.set_execution_config(enable_result_cache=False)

    def query():
        df = dt.from_pydict({"k": ["a", "b", "c"] * 200,
                             "v": list(range(600))})
        return (df.where(col("v") > 3).into_partitions(3)
                .groupby("k").agg(col("v").sum().alias("s")).sort("k"))

    # 1: QueryRecord on a plain collect
    before = len(dt.query_log())
    q = query().collect()
    rec = q.last_query_record()
    if rec is None:
        print("obs-smoke: FAIL — collect() appended no QueryRecord")
        return 1
    errs = validate_record(rec)
    if errs:
        print(f"obs-smoke: FAIL — record schema: {errs}")
        return 1
    if rec["outcome"] != "ok" or not rec["plan_fingerprint"]:
        print(f"obs-smoke: FAIL — bad record {rec['outcome']!r}")
        return 1
    log = dt.query_log()
    if len(log) <= before or log[-1] is not rec:
        print("obs-smoke: FAIL — record missing from dt.query_log()")
        return 1

    # 2: health snapshot
    h = dt.health()
    errs = validate_health(h)
    if errs:
        print(f"obs-smoke: FAIL — health schema: {errs}")
        return 1
    if not {"device", "collective"} <= set(h["breakers"]):
        print(f"obs-smoke: FAIL — breakers missing: {h['breakers']}")
        return 1

    # 3: forced slow-query bundle + auto-arm on the second run
    tmp = tempfile.mkdtemp(prefix="daft_tpu_obs_smoke_")
    dt.set_execution_config(slow_query_threshold_s=0.0, diagnostics_dir=tmp)
    try:
        r1 = query().collect().last_query_record()
        r2 = query().collect().last_query_record()
    finally:
        dt.set_execution_config(slow_query_threshold_s=None,
                                diagnostics_dir=None)
    bundles = list_bundles(tmp)
    if len(bundles) < 2:
        print(f"obs-smoke: FAIL — expected 2 bundles, got {bundles}")
        return 1
    last = os.path.join(tmp, bundles[-1])
    files = set(os.listdir(last))
    if not {"record.json", "stats.txt"} <= files:
        print(f"obs-smoke: FAIL — bundle incomplete: {sorted(files)}")
        return 1
    errs = validate_record(json.load(open(os.path.join(last, "record.json"))))
    if errs:
        print(f"obs-smoke: FAIL — bundle record schema: {errs}")
        return 1
    if not r2["profiled"] or "profile.json" not in files:
        print("obs-smoke: FAIL — second slow run was not auto-profiled "
              f"(profiled={r2['profiled']}, files={sorted(files)})")
        return 1
    if r1["plan_fingerprint"] != r2["plan_fingerprint"]:
        print("obs-smoke: FAIL — plan fingerprint unstable across runs")
        return 1

    # 4: health/ledger gauges in the metrics dump
    text = dt.metrics_text()
    for name in ("daft_tpu_query_log_depth",
                 "daft_tpu_memory_ledger_bytes",
                 "daft_tpu_memory_ledger_prefetch_inflight_bytes",
                 "daft_tpu_device_breaker_state",
                 "daft_tpu_scheduler_inflight_tasks"):
        if name not in text:
            print(f"obs-smoke: FAIL — metrics dump missing {name}")
            return 1

    # 5: structured-log line for the bundle, with query_id
    lines = [r for r in obs_log.tail(500)
             if r["event"] == "diagnostics_bundle"]
    if not lines or "query_id" not in lines[-1]:
        print("obs-smoke: FAIL — no attributed diagnostics_bundle log line")
        return 1

    # 6: distributed leg — one merged trace across 2 worker processes
    from daft_tpu.context import get_context
    from daft_tpu.dist import supervisor as sup
    from daft_tpu.profile.export import validate_profile

    cfg = get_context().execution_config
    cfg.distributed_workers = 2
    try:
        d = dt.from_pydict({"k": list(range(6000)),
                            "g": [i % 17 for i in range(6000)]})
        q2 = (d.repartition(4)
              .select(col("g"), (col("k") * col("g")).alias("kg"))
              .where(col("kg") % 3 != 0)
              .groupby("g").agg(col("kg").sum().alias("s")).sort("g"))
        got = q2.collect(profile=True)
        prof = got.profile()
        data = prof.to_dict() if prof is not None else None
        if data is None or validate_profile(data):
            print("obs-smoke: FAIL — distributed QueryProfile invalid: "
                  f"{None if data is None else validate_profile(data)}")
            return 1
        if data["orphan_spans"]:
            print(f"obs-smoke: FAIL — {data['orphan_spans']} orphan "
                  "span(s) in the merged distributed profile")
            return 1
        lanes = {s["thread"] for s in data["spans"]
                 if s["thread"].startswith("worker-")}
        if len(lanes) < 2:
            print(f"obs-smoke: FAIL — expected >=2 per-worker chrome "
                  f"lanes, got {sorted(lanes)}")
            return 1
        names = {s["name"] for s in data["spans"]}
        if "dist.remote" not in names or "worker.task" not in names:
            print(f"obs-smoke: FAIL — remote spans missing from "
                  f"{sorted(names)[:10]}")
            return 1
        orphan_worker_lines = [
            r for r in obs_log.tail(10**6)
            if "relay_worker" in r and "query_id" not in r]
        if orphan_worker_lines:
            print("obs-smoke: FAIL — worker log lines without query_id: "
                  f"{orphan_worker_lines[:2]}")
            return 1
        c = got.stats.snapshot()["counters"]
        if not c.get("telemetry_merged"):
            print("obs-smoke: FAIL — no telemetry fragment merged on the "
                  "distributed leg")
            return 1
    finally:
        cfg.distributed_workers = 0
        sup.shutdown_worker_pool()
    if sup.live_worker_process_count():
        print("obs-smoke: FAIL — leaked worker processes")
        return 1

    print(f"obs-smoke: OK — {len(dt.query_log())} record(s), "
          f"{len(bundles)} bundle(s), auto-armed profile on run 2, "
          f"{len(lanes)} worker lane(s) in the merged profile, "
          f"{len(obs_log.tail(10**6))} log record(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
